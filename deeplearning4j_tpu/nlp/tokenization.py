"""Tokenization + sentence iteration pipeline.

Reference parity: `text/tokenization/` (TokenizerFactory SPI,
DefaultTokenizer, CommonPreprocessor lowercase/punct-strip) and
`text/sentenceiterator/` (13 impls in the reference; the load-bearing ones
here: collection, file, line).
"""

from __future__ import annotations

import os
import re
from typing import Callable, Iterable, Iterator, List, Optional, Sequence


class TokenPreProcess:
    """Reference: `tokenization/tokenizer/TokenPreProcess`."""

    def pre_process(self, token: str) -> str:
        return token


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits. Reference:
    `tokenizer/preprocessor/CommonPreprocessor`."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class Tokenizer:
    def __init__(self, text: str, pre: Optional[TokenPreProcess] = None):
        self._tokens = [t for t in text.split() if t]
        self._pre = pre

    def tokens(self) -> List[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out


class TokenizerFactory:
    """Reference: `tokenization/tokenizerfactory/TokenizerFactory` SPI."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre
        return self

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text, self._pre)


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer. Reference: DefaultTokenizerFactory."""


class NGramTokenizerFactory(TokenizerFactory):
    """Reference: NGramTokenizerFactory — emits n-grams joined by '_'."""

    def __init__(self, n_min: int = 1, n_max: int = 2):
        super().__init__()
        self.n_min, self.n_max = n_min, n_max

    def create(self, text: str) -> Tokenizer:
        base = Tokenizer(text, self._pre).tokens()
        out = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(base) - n + 1):
                out.append("_".join(base[i:i + n]))
        t = Tokenizer("", None)
        t._tokens = out
        return t


class SentenceIterator:
    """Reference: `text/sentenceiterator/SentenceIterator` (incl. the
    setPreProcessor seam — every sentence passes through it)."""

    _pre = None  # SentencePreProcessor

    def set_pre_processor(self, pre) -> "SentenceIterator":
        """Reference: SentenceIterator.setPreProcessor."""
        self._pre = pre
        return self

    def _apply_pre(self, sentence: str) -> str:
        return self._pre.pre_process(sentence) if self._pre else sentence

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str]):
        self._s = list(sentences)

    def __iter__(self):
        for s in self._s:
            yield self._apply_pre(s)


class FileSentenceIterator(SentenceIterator):
    """Iterate sentences (lines) of every file under a directory.
    Reference: FileSentenceIterator."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        if os.path.isfile(self.path):
            files = [self.path]
        else:
            files = sorted(
                os.path.join(d, f)
                for d, _, fs in os.walk(self.path) for f in fs)
        for fp in files:
            with open(fp, "r", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield self._apply_pre(line)


class LineSentenceIterator(FileSentenceIterator):
    """Reference: LineSentenceIterator (single file, line per sentence)."""


class BasicLineIterator(LineSentenceIterator):
    """Reference: BasicLineIterator — the workhorse single-file iterator."""


class StreamLineIterator(SentenceIterator):
    """Iterate lines of an already-open text stream (reference:
    StreamLineIterator). The stream is drained once; reset() replays the
    buffered lines."""

    def __init__(self, stream):
        self._lines = [l.strip() for l in stream if l.strip()]

    def __iter__(self):
        for l in self._lines:
            yield self._apply_pre(l)


class AggregatingSentenceIterator(SentenceIterator):
    """Chain several sentence iterators (reference:
    AggregatingSentenceIterator.Builder)."""

    def __init__(self, *iterators: SentenceIterator):
        self._its = list(iterators)

    def __iter__(self):
        for it in self._its:
            for s in it:
                yield self._apply_pre(s)

    def reset(self):
        for it in self._its:
            it.reset()


class MultipleEpochsSentenceIterator(SentenceIterator):
    """Replay an iterator N times (reference:
    MutipleEpochsSentenceIterator — [sic] the reference's typo)."""

    def __init__(self, inner: SentenceIterator, epochs: int):
        self._inner = inner
        self.epochs = epochs

    def __iter__(self):
        for _ in range(self.epochs):
            self._inner.reset()
            for s in self._inner:
                yield self._apply_pre(s)


class PrefetchingSentenceIterator(SentenceIterator):
    """Background-thread prefetch through a bounded queue (reference:
    PrefetchingSentenceIterator) — overlaps disk IO with tokenization."""

    def __init__(self, inner: SentenceIterator, buffer: int = 1024):
        self._inner = inner
        self.buffer = buffer

    def __iter__(self):
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.buffer)
        _END = object()
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up when the consumer went away, so an
            # abandoned iteration can't leak a blocked producer thread
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for s in self._inner:
                    if not put(s):
                        return
                put(_END)
            except BaseException as e:  # surfaced to the consumer
                put(("__error__", e))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, tuple) and len(item) == 2 and \
                        item[0] == "__error__":
                    raise item[1]
                yield self._apply_pre(item)
        finally:
            stop.set()

    def reset(self):
        self._inner.reset()


class LabelAwareSentenceIterator(SentenceIterator):
    """Sentence iterator that also exposes the current sentence's label
    (reference: labelaware/LabelAwareSentenceIterator SPI)."""

    def current_label(self) -> str:
        raise NotImplementedError


class LabelAwareListSentenceIterator(LabelAwareSentenceIterator):
    """Sentences + parallel labels (reference:
    labelaware/LabelAwareListSentenceIterator)."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[str]):
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels must align")
        self._s = list(sentences)
        self._labels = list(labels)
        self._pos = -1

    def __iter__(self):
        for i, s in enumerate(self._s):
            self._pos = i
            yield self._apply_pre(s)

    def current_label(self) -> str:
        if self._pos < 0:
            raise RuntimeError(
                "current_label() before iteration — pull a sentence first")
        return self._labels[self._pos]


def tokenize_corpus(sentences: Iterable[str],
                    factory: Optional[TokenizerFactory] = None
                    ) -> List[List[str]]:
    factory = factory or DefaultTokenizerFactory()
    return [factory.create(s).tokens() for s in sentences]
