"""Tokenization + sentence iteration pipeline.

Reference parity: `text/tokenization/` (TokenizerFactory SPI,
DefaultTokenizer, CommonPreprocessor lowercase/punct-strip) and
`text/sentenceiterator/` (13 impls in the reference; the load-bearing ones
here: collection, file, line).
"""

from __future__ import annotations

import os
import re
from typing import Callable, Iterable, Iterator, List, Optional, Sequence


class TokenPreProcess:
    """Reference: `tokenization/tokenizer/TokenPreProcess`."""

    def pre_process(self, token: str) -> str:
        return token


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits. Reference:
    `tokenizer/preprocessor/CommonPreprocessor`."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class Tokenizer:
    def __init__(self, text: str, pre: Optional[TokenPreProcess] = None):
        self._tokens = [t for t in text.split() if t]
        self._pre = pre

    def tokens(self) -> List[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out


class TokenizerFactory:
    """Reference: `tokenization/tokenizerfactory/TokenizerFactory` SPI."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre
        return self

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text, self._pre)


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer. Reference: DefaultTokenizerFactory."""


class NGramTokenizerFactory(TokenizerFactory):
    """Reference: NGramTokenizerFactory — emits n-grams joined by '_'."""

    def __init__(self, n_min: int = 1, n_max: int = 2):
        super().__init__()
        self.n_min, self.n_max = n_min, n_max

    def create(self, text: str) -> Tokenizer:
        base = Tokenizer(text, self._pre).tokens()
        out = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(base) - n + 1):
                out.append("_".join(base[i:i + n]))
        t = Tokenizer("", None)
        t._tokens = out
        return t


class SentenceIterator:
    """Reference: `text/sentenceiterator/SentenceIterator`."""

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str]):
        self._s = list(sentences)

    def __iter__(self):
        return iter(self._s)


class FileSentenceIterator(SentenceIterator):
    """Iterate sentences (lines) of every file under a directory.
    Reference: FileSentenceIterator."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        if os.path.isfile(self.path):
            files = [self.path]
        else:
            files = sorted(
                os.path.join(d, f)
                for d, _, fs in os.walk(self.path) for f in fs)
        for fp in files:
            with open(fp, "r", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield line


class LineSentenceIterator(FileSentenceIterator):
    """Reference: LineSentenceIterator (single file, line per sentence)."""


def tokenize_corpus(sentences: Iterable[str],
                    factory: Optional[TokenizerFactory] = None
                    ) -> List[List[str]]:
    factory = factory or DefaultTokenizerFactory()
    return [factory.create(s).tokens() for s in sentences]
