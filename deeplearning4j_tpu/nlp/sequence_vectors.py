"""SequenceVectors — the generic sequence-embedding trainer SPI.

Reference parity: `models/sequencevectors/SequenceVectors.java:51` — ONE
trainer (vocab build → Huffman/negative-sampling tables → training loop)
shared by every embedding model (Word2Vec, ParagraphVectors, DeepWalk,
Node2Vec), parameterized by an `ElementsLearningAlgorithm` /
`SequenceLearningAlgorithm` SPI (`:58-59`).

TPU redesign (SURVEY §7 hard part (c)): the reference spawns N hogwild
`VectorCalculationsThread`s doing lock-free updates into shared syn0/syn1;
here pair generation is vectorized host-side and each learning algorithm
contributes ONE jitted step over ~10⁴ pairs (gathers + autodiff
scatter-adds + SGD with the classic linear LR decay). Concrete algorithms:
`SkipGram`, `CBOW` (element-level; both with hierarchical-softmax and
negative-sampling variants). Sequence-level DBOW/DM live in
ParagraphVectors over this same engine, and DeepWalk drives it with
degree-weighted vocab counts — nothing re-implements the loop.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence as Seq, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import (
    HuffmanTree, VocabCache, VocabWord, build_vocab, unigram_table,
)


@dataclasses.dataclass
class SequenceElement:
    """Reference: `sequencevectors/sequence/SequenceElement` — anything
    with a label and a frequency can be embedded (words, vertices,
    labels)."""

    label: str
    count: int = 1


@dataclasses.dataclass
class Sequence:
    """Reference: `sequencevectors/sequence/Sequence` — an ordered list of
    elements, optionally carrying sequence-level labels (doc2vec)."""

    elements: List[str]
    labels: List[str] = dataclasses.field(default_factory=list)


class AbstractSequenceIterator:
    """Reference: `interfaces/SequenceIterator` +
    AbstractSequenceIterator.Builder — adapts any iterable of sequences."""

    def __init__(self, sequences: Iterable):
        self._seqs = list(sequences)

    def __iter__(self):
        for s in self._seqs:
            yield s if isinstance(s, Sequence) else Sequence(list(s))

    def reset(self):
        pass


# ---------------------------------------------------------------- SPI
class ElementsLearningAlgorithm:
    """Reference: `learning/ElementsLearningAlgorithm` — pluggable
    per-element trainer. Implementations supply the jitted step."""

    name = "abstract"

    def make_step(self, model: "SequenceVectors", hs_tables=None):
        """Return a jitted step. Negative-sampling signature:
        step(params, centers, contexts, negatives, lr); hierarchical
        softmax: step(params, centers, contexts, lr)."""
        raise NotImplementedError


class SkipGram(ElementsLearningAlgorithm):
    """Center predicts context. Reference:
    `learning/impl/elements/SkipGram.java` (AggregateSkipGram batches)."""

    name = "skipgram"
    cbow = False

    def make_step(self, model, hs_tables=None):
        if model.hs:
            codes, points, lens = hs_tables
            return _hs_step(codes, points, lens)
        return _ns_step(cbow=self.cbow)


class CBOW(SkipGram):
    """Context predicts center. Reference:
    `learning/impl/elements/CBOW.java`."""

    name = "cbow"
    cbow = True


LEARNING_ALGORITHMS: Dict[str, type] = {
    "skipgram": SkipGram, "cbow": CBOW,
}


def _ns_step(cbow: bool):
    @jax.jit
    # graft: allow(GL102): factory runs once per fit(); the trainer
    # caches the returned jitted step for the whole epoch loop
    def step(params, centers, contexts, negatives, lr):
        def loss_fn(p):
            s0, s1 = p["syn0"], p["syn1"]
            h = s0[contexts] if cbow else s0[centers]
            tgt = centers if cbow else contexts
            pos = jnp.einsum("bd,bd->b", h, s1[tgt])
            neg = jnp.einsum("bd,bkd->bk", h, s1[negatives])
            # SUM (not mean): per-pair update magnitude matches the
            # reference's per-example SGD semantics.
            return (jnp.sum(jax.nn.softplus(-pos))
                    + jnp.sum(jax.nn.softplus(neg)))

        grads = jax.grad(loss_fn)(params)
        return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)

    return step


def _hs_step(codes, points, lens):
    codes = jnp.asarray(codes)
    points = jnp.asarray(points)
    lens = jnp.asarray(lens)

    @jax.jit
    # graft: allow(GL102): factory runs once per fit(); the trainer
    # caches the returned jitted step for the whole epoch loop
    def step(params, centers, contexts, lr):
        def loss_fn(p):
            h = p["syn0"][centers]                     # [B,D]
            pt = points[contexts]                      # [B,L]
            cd = codes[contexts].astype(jnp.float32)   # [B,L]
            ln = lens[contexts]                        # [B]
            L = pt.shape[1]
            valid = jnp.arange(L)[None, :] < ln[:, None]
            logits = jnp.einsum("bd,bld->bl", h, p["syn1"][pt])
            # code bit 1 → sigmoid target 0 (word2vec convention)
            bce = jnp.where(valid, jax.nn.softplus(
                jnp.where(cd > 0, logits, -logits)), 0.0)
            return jnp.sum(bce)

        grads = jax.grad(loss_fn)(params)
        return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)

    return step


# ------------------------------------------------------------- trainer
class SequenceVectors:
    """Reference: `SequenceVectors.java` Builder surface mapped to kwargs
    (`fit():187` = vocab build → Huffman → training)."""

    def __init__(self, *, layer_size: int = 100, window: int = 5,
                 min_count: int = 5, negative: int = 5,
                 hierarchic_softmax: bool = False,
                 subsampling: float = 1e-3, epochs: int = 1,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 batch_size: int = 8192, seed: int = 42,
                 dynamic_window: bool = True,
                 learning_algorithm="skipgram"):
        self.layer_size = layer_size
        self.window = window
        self.min_count = min_count
        self.negative = negative
        self.hs = hierarchic_softmax
        self.subsampling = subsampling
        self.epochs = epochs
        self.lr = learning_rate
        self.min_lr = min_learning_rate
        self.batch_size = batch_size
        self.seed = seed
        self.dynamic_window = dynamic_window
        if isinstance(learning_algorithm, str):
            if learning_algorithm not in LEARNING_ALGORITHMS:
                raise ValueError(
                    f"Unknown learning algorithm {learning_algorithm!r}; "
                    f"known: {sorted(LEARNING_ALGORITHMS)}")
            learning_algorithm = LEARNING_ALGORITHMS[learning_algorithm]()
        self.algorithm: ElementsLearningAlgorithm = learning_algorithm
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None
        self._syn1: Optional[np.ndarray] = None
        # optional warm-start tables (DeepWalk.initialize() pre-allocates)
        self.initial_syn0: Optional[np.ndarray] = None
        self.initial_syn1: Optional[np.ndarray] = None

    # back-compat alias used by a few call sites / subclasses
    @property
    def cbow(self) -> bool:
        return getattr(self.algorithm, "cbow", False)

    # ------------------------------------------------------------ fitting
    def fit(self, sequences: Iterable,
            element_counts: Optional[Dict[str, int]] = None
            ) -> "SequenceVectors":
        """Train on sequences of string elements. `element_counts`
        overrides vocab frequencies (DeepWalk passes vertex degrees — the
        reference's GraphHuffman-over-degrees becomes the standard
        count-based Huffman path)."""
        seqs = [list(s.elements) if isinstance(s, Sequence) else list(s)
                for s in sequences]
        if element_counts is not None:
            self.vocab = VocabCache()
            for label, count in element_counts.items():
                self.vocab.add(VocabWord(word=str(label), count=int(count)))
        else:
            self.vocab = build_vocab(seqs, min_count=self.min_count)
        if len(self.vocab) == 0:
            raise ValueError("Empty vocabulary (min_count too high?)")
        return self._fit_engine(self._index_sequences(seqs))

    def fit_indexed(self, idx_sequences, counts) -> "SequenceVectors":
        """Fast path for sequences that are ALREADY vocab indices 0..V-1
        with per-index frequencies `counts` (DeepWalk's walk matrices) —
        skips the per-element string lookups entirely."""
        self.vocab = VocabCache()
        for i, c in enumerate(np.asarray(counts)):
            self.vocab.add(VocabWord(word=str(i), count=int(c)))
        idx = [np.asarray(s, np.int64) for s in idx_sequences]
        return self._fit_engine([s for s in idx if len(s) > 1])

    def _fit_engine(self, idx_sequences) -> "SequenceVectors":
        rng = np.random.default_rng(self.seed)
        setup = self._setup(rng)
        params = setup["params"]
        total_est = sum(len(s) for s in idx_sequences) * self.window \
            * max(self.epochs, 1)
        seen = 0
        for _ in range(self.epochs):
            params, seen = self._run_epoch(
                params, idx_sequences, setup, rng, seen, total_est)
        self.syn0 = np.asarray(params["syn0"])
        self._syn1 = np.asarray(params["syn1"])
        return self

    def _index_sequences(self, sequences):
        idx = [
            np.array([self.vocab.index_of(w) for w in s], dtype=np.int64)
            for s in sequences
        ]
        return [s[s >= 0] for s in idx if (s >= 0).sum() > 1]

    _index_sentences = _index_sequences  # word-flavored alias

    def _setup(self, rng=None):
        """Allocate syn0/syn1 + build the algorithm's jit step from
        self.vocab. Shared by local fit() and the distributed trainer."""
        V, D = len(self.vocab), self.layer_size
        if rng is None:
            rng = np.random.default_rng(self.seed)
        syn0 = (self.initial_syn0 if self.initial_syn0 is not None
                else (rng.random((V, D), dtype=np.float32) - 0.5) / D)
        syn1 = np.zeros((V, D), dtype=np.float32)
        probs = unigram_table(self.vocab)
        counts = self.vocab.counts()
        total = counts.sum()
        hs_tables = None
        if self.hs:
            HuffmanTree(self.vocab)
            hs_tables = HuffmanTree.padded_codes(self.vocab)
            syn1 = np.zeros((max(V - 1, 1), D), dtype=np.float32)
        if self.initial_syn1 is not None:
            syn1 = self.initial_syn1
        step = self.algorithm.make_step(self, hs_tables)
        # subsampling keep probability (word2vec formula)
        t = self.subsampling
        freq = counts / max(total, 1)
        keep = (np.sqrt(freq / t) + 1) * (t / np.maximum(freq, 1e-12)) \
            if t > 0 else np.ones(V)
        params = {"syn0": jnp.asarray(syn0), "syn1": jnp.asarray(syn1)}
        return {"params": params, "keep": np.clip(keep, 0, 1),
                "probs": probs, "step": step}

    def _run_epoch(self, params, idx_sequences, setup, rng, seen, total_est):
        """One pass over idx_sequences; returns (params, seen)."""
        keep, probs, step = setup["keep"], setup["probs"], setup["step"]
        centers, contexts = self._generate_pairs(idx_sequences, keep, rng)
        order = rng.permutation(len(centers))
        centers, contexts = centers[order], contexts[order]
        for lo in range(0, len(centers), self.batch_size):
            c = centers[lo:lo + self.batch_size]
            x = contexts[lo:lo + self.batch_size]
            # NOTE: the trailing partial batch trains at its natural size
            # (one extra XLA compile per distinct tail length, bounded at
            # one per corpus) — dropping it would silently skip data, and
            # tiny corpora would not train at all.
            frac = min(seen / max(total_est, 1), 1.0)
            lr = max(self.lr * (1.0 - frac), self.min_lr)
            if self.hs:
                params = step(params, jnp.asarray(c), jnp.asarray(x),
                              jnp.asarray(lr, jnp.float32))
            else:
                negs = rng.choice(len(probs),
                                  size=(len(c), self.negative), p=probs)
                params = step(params, jnp.asarray(c), jnp.asarray(x),
                              jnp.asarray(negs), jnp.asarray(lr, jnp.float32))
            seen += len(c)
        return params, seen

    def _generate_pairs(self, idx_sequences, keep, rng
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """(center, context) pairs with frequency subsampling — vectorized
        host-side equivalent of the reference's per-thread sequence walk.
        dynamic_window=True shrinks each center's window uniformly (the
        word2vec convention); False uses the full window (DeepWalk)."""
        all_c, all_x = [], []
        for s in idx_sequences:
            if self.subsampling > 0:
                s = s[rng.random(len(s)) < keep[s]]
            n = len(s)
            if n < 2:
                continue
            if self.dynamic_window:
                b = rng.integers(1, self.window + 1, n)
            else:
                b = np.full(n, self.window)
            for off in range(1, self.window + 1):
                if n <= off:
                    break
                i = np.arange(n - off)
                m = b[i + off] >= off     # center i+off ← context i
                all_c.append(s[i + off][m])
                all_x.append(s[i][m])
                m = b[i] >= off           # center i ← context i+off
                all_c.append(s[i][m])
                all_x.append(s[i + off][m])
        if not all_c:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)
        return np.concatenate(all_c), np.concatenate(all_x)

    # ------------------------------------------------------------ queries
    def element_vector(self, label: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(label)
        return None if i < 0 else self.syn0[i]

    # word-flavored aliases (reference: WordVectors interface)
    word_vector = element_vector

    def similarity(self, a: str, b: str) -> float:
        """Reference: `WordVectors.similarity`."""
        va, vb = self.element_vector(a), self.element_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12
        return float(va @ vb / denom)

    def words_nearest(self, label_or_vec, n: int = 10) -> List[str]:
        """Reference: `WordVectors.wordsNearest`."""
        if isinstance(label_or_vec, str):
            v = self.element_vector(label_or_vec)
            exclude = {self.vocab.index_of(label_or_vec)}
            if v is None:
                return []
        else:
            v = np.asarray(label_or_vec, np.float32)
            exclude = set()
        norms = np.linalg.norm(self.syn0, axis=1) + 1e-12
        sims = self.syn0 @ v / (norms * (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            if i in exclude:
                continue
            out.append(self.vocab.word_at(int(i)))
            if len(out) >= n:
                break
        return out

    elements_nearest = words_nearest

    def accuracy(self, questions: Seq[Tuple[str, str, str, str]]) -> float:
        """Analogy accuracy (a:b :: c:d). Reference: Word2Vec accuracy
        tests."""
        good = total = 0
        for a, b, c, d in questions:
            va, vb, vc = (self.element_vector(w) for w in (a, b, c))
            if va is None or vb is None or vc is None:
                continue
            pred = self.words_nearest(vb - va + vc, 4)
            total += 1
            if d in pred:
                good += 1
        return good / max(total, 1)
