"""Stop-word list + filtering preprocessor.

Reference parity: `deeplearning4j-nlp/src/main/resources/stopwords.txt`
loaded by `text/stopwords/StopWords.java` (getStopWords()) and applied in
the Word2Vec/vocab pipelines. The embedded list here is the standard
English closed-class set (articles, pronouns, auxiliaries, prepositions,
conjunctions — the usual NLTK-style inventory), not a copy of the
reference resource; `StopWords.get_stop_words(extra=...)` extends it.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set

from deeplearning4j_tpu.nlp.tokenization import TokenPreProcess

_ENGLISH = """
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for
from further had hadn't has hasn't have haven't having he he'd he'll he's
her here here's hers herself him himself his how how's i i'd i'll i'm
i've if in into is isn't it it's its itself let's me more most mustn't
my myself no nor not of off on once only or other ought our ours
ourselves out over own same shan't she she'd she'll she's should
shouldn't so some such than that that's the their theirs them themselves
then there there's these they they'd they'll they're they've this those
through to too under until up very was wasn't we we'd we'll we're we've
were weren't what what's when when's where where's which while who who's
whom why why's with won't would wouldn't you you'd you'll you're you've
your yours yourself yourselves
""".split()


class StopWords:
    """Reference: `text/stopwords/StopWords.java` — getStopWords()."""

    @staticmethod
    def get_stop_words(extra: Optional[Iterable[str]] = None) -> List[str]:
        return list(_ENGLISH) + (list(extra) if extra else [])


class StopWordsRemovalPreprocessor(TokenPreProcess):
    """TokenPreProcess mapping stop words to "" (tokenizers drop empty
    tokens), composing with any inner preprocessor — how the reference
    pipelines filter stop words before vocab construction.

    The stop set is normalized THROUGH the inner preprocessor, so e.g.
    CommonPreprocessor stripping apostrophes ("don't" -> "dont") can't
    let contraction stop words slip past the lookup."""

    def __init__(self, stop_words: Optional[Iterable[str]] = None,
                 inner: Optional[TokenPreProcess] = None,
                 case_sensitive: bool = False):
        words = (list(stop_words) if stop_words is not None
                 else StopWords.get_stop_words())
        self.case_sensitive = case_sensitive
        self.inner = inner
        norm = (lambda w: w) if case_sensitive else str.lower
        self._set: Set[str] = set()
        for w in words:
            self._set.add(norm(w))
            if inner is not None:
                self._set.add(norm(inner.pre_process(w)))
        self._set.discard("")

    def pre_process(self, token: str) -> str:
        if self.inner is not None:
            token = self.inner.pre_process(token)
        key = token if self.case_sensitive else token.lower()
        return "" if key in self._set else token
