"""Vocabulary construction + Huffman coding.

Reference parity: `models/word2vec/wordstore/` (VocabCache, VocabConstructor,
VocabularyHolder) and Huffman tree building in
`models/word2vec/Huffman.java` — word counts, min-frequency pruning,
special tokens, binary Huffman codes/points for hierarchical softmax.
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class VocabWord:
    """Reference: `models/word2vec/VocabWord`."""

    word: str
    count: int = 0
    index: int = -1
    code: Optional[List[int]] = None    # Huffman code bits
    points: Optional[List[int]] = None  # Huffman inner-node indices


class VocabCache:
    """Reference: `wordstore/VocabCache` — index/word/count store."""

    def __init__(self):
        self.words: List[VocabWord] = []
        self._index: Dict[str, int] = {}
        self.total_count = 0

    def add(self, vw: VocabWord) -> None:
        vw.index = len(self.words)
        self.words.append(vw)
        self._index[vw.word] = vw.index
        self.total_count += vw.count

    def __contains__(self, word: str) -> bool:
        return word in self._index

    def __len__(self) -> int:
        return len(self.words)

    def index_of(self, word: str) -> int:
        return self._index.get(word, -1)

    def word_at(self, idx: int) -> str:
        return self.words[idx].word

    def count_of(self, word: str) -> int:
        i = self.index_of(word)
        return self.words[i].count if i >= 0 else 0

    def counts(self) -> np.ndarray:
        return np.array([w.count for w in self.words], dtype=np.int64)


def build_vocab(sentences: Iterable[Sequence[str]], *, min_count: int = 5,
                max_size: Optional[int] = None) -> VocabCache:
    """Corpus scan → pruned, frequency-sorted vocab. Reference:
    `wordstore/inmemory/VocabConstructor` (min word frequency)."""
    counts = Counter()
    for s in sentences:
        counts.update(s)
    vocab = VocabCache()
    items = [(w, c) for w, c in counts.items() if c >= min_count]
    items.sort(key=lambda t: (-t[1], t[0]))
    if max_size:
        items = items[:max_size]
    for w, c in items:
        vocab.add(VocabWord(word=w, count=c))
    return vocab


class HuffmanTree:
    """Binary Huffman coding over vocab counts. Reference:
    `models/word2vec/Huffman.java` — assigns each word a bit code and the
    list of inner-node indices (points) on its root path, used by
    hierarchical softmax."""

    def __init__(self, vocab: VocabCache):
        n = len(vocab)
        self.n_inner = max(n - 1, 1)
        if n == 0:
            return
        heap: List[Tuple[int, int]] = [(w.count, i) for i, w in
                                       enumerate(vocab.words)]
        heapq.heapify(heap)
        parent = {}
        binary = {}
        next_id = n
        while len(heap) > 1:
            c1, i1 = heapq.heappop(heap)
            c2, i2 = heapq.heappop(heap)
            parent[i1] = next_id
            parent[i2] = next_id
            binary[i1] = 0
            binary[i2] = 1
            heapq.heappush(heap, (c1 + c2, next_id))
            next_id += 1
        root = heap[0][1]
        for i, vw in enumerate(vocab.words):
            code, points = [], []
            node = i
            while node != root:
                code.append(binary[node])
                p = parent[node]
                points.append(p - n)  # inner-node index in [0, n-1)
                node = p
            vw.code = list(reversed(code))
            vw.points = list(reversed(points))

    @staticmethod
    def padded_codes(vocab: VocabCache, max_len: Optional[int] = None
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(codes [V,L], points [V,L], lengths [V]) padded for batched
        hierarchical-softmax on device."""
        lens = np.array([len(w.code or []) for w in vocab.words])
        L = int(max_len or (lens.max() if len(lens) else 1))
        V = len(vocab)
        codes = np.zeros((V, L), dtype=np.int32)
        points = np.zeros((V, L), dtype=np.int32)
        for i, w in enumerate(vocab.words):
            c = (w.code or [])[:L]
            p = (w.points or [])[:L]
            codes[i, :len(c)] = c
            points[i, :len(p)] = p
        return codes, points, np.minimum(lens, L)


def unigram_table(vocab: VocabCache, power: float = 0.75) -> np.ndarray:
    """Negative-sampling distribution (counts^0.75) — reference: the unigram
    table in InMemoryLookupTable. Returned as a probability vector (we sample
    with np.random.choice instead of the reference's 100M-slot table)."""
    c = vocab.counts().astype(np.float64) ** power
    return (c / c.sum()).astype(np.float64)
