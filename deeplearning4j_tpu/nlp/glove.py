"""GloVe — co-occurrence counting + weighted-least-squares embedding fit.

Reference parity: `models/glove/Glove.java` + `models/glove/count/`
(co-occurrence map) and the AdaGrad element updates in
`models/embeddings/learning/impl/elements/GloVe.java`. Counting stays on
host (hash map, like the reference's CountMap); the optimization is batched
AdaGrad in one jitted step over (i, j, X_ij) triples.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, _as_token_lists


class Glove(Word2Vec):
    def __init__(self, *, x_max: float = 100.0, alpha: float = 0.75, **kw):
        kw.setdefault("learning_rate", 0.05)
        super().__init__(**kw)
        self.x_max = x_max
        self.alpha = alpha

    def fit(self, corpus) -> "Glove":
        sentences = _as_token_lists(corpus, self.tokenizer_factory)
        self.vocab = build_vocab(sentences, min_count=self.min_count)
        V, D = len(self.vocab), self.layer_size

        # ---- co-occurrence accumulation (reference: CountMap/RoundCount)
        cooc: Dict[Tuple[int, int], float] = defaultdict(float)
        for s in sentences:
            idx = [self.vocab.index_of(w) for w in s]
            idx = [i for i in idx if i >= 0]
            for pos, ci in enumerate(idx):
                lo = max(0, pos - self.window)
                for off, cj in enumerate(idx[lo:pos]):
                    dist = pos - (lo + off)
                    w = 1.0 / dist
                    a, b = (ci, cj) if ci <= cj else (cj, ci)
                    cooc[(a, b)] += w
        if not cooc:
            raise ValueError("No co-occurrences")
        pairs = np.array(list(cooc.keys()), dtype=np.int64)
        xij = np.array(list(cooc.values()), dtype=np.float32)

        rng = np.random.default_rng(self.seed)
        params = {
            "w": jnp.asarray((rng.random((V, D), dtype=np.float32) - .5) / D),
            "wc": jnp.asarray((rng.random((V, D), dtype=np.float32) - .5) / D),
            "b": jnp.zeros((V,), jnp.float32),
            "bc": jnp.zeros((V,), jnp.float32),
        }
        hist = jax.tree_util.tree_map(
            lambda a: jnp.ones_like(a) * 1e-8, params)
        x_max, alpha, lr = self.x_max, self.alpha, self.lr

        @jax.jit
        # graft: allow(GL102): compiled once per fit(); closes over
        # per-fit hyperparameters and lives for the whole epoch loop
        def step(params, hist, ii, jj, x):
            def loss_fn(p):
                dot = jnp.einsum("bd,bd->b", p["w"][ii], p["wc"][jj])
                pred = dot + p["b"][ii] + p["bc"][jj]
                fw = jnp.minimum((x / x_max) ** alpha, 1.0)
                return jnp.sum(fw * (pred - jnp.log(jnp.maximum(x, 1e-10))) ** 2)

            grads = jax.grad(loss_fn)(params)
            new_hist = jax.tree_util.tree_map(
                lambda h, g: h + g * g, hist, grads)
            new_params = jax.tree_util.tree_map(
                lambda p, g, h: p - lr * g / jnp.sqrt(h),
                params, grads, new_hist)
            return new_params, new_hist

        for _ in range(max(self.epochs, 1)):
            order = rng.permutation(len(xij))
            for lo in range(0, len(order), self.batch_size):
                sel = order[lo:lo + self.batch_size]
                params, hist = step(params, hist,
                                    jnp.asarray(pairs[sel, 0]),
                                    jnp.asarray(pairs[sel, 1]),
                                    jnp.asarray(xij[sel]))

        self.syn0 = np.asarray(params["w"] + params["wc"])  # standard sum
        self._syn1 = np.asarray(params["wc"])
        return self
