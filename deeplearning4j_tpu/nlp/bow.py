"""Bag-of-words / TF-IDF vectorizers.

Reference parity: `bagofwords/vectorizer/` (BagOfWordsVectorizer,
TfidfVectorizer) — corpus → fixed-width count/tf-idf feature arrays keyed by
a VocabCache.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab


class BagOfWordsVectorizer:
    def __init__(self, *, min_count: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_count = min_count
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None

    def _tokens(self, texts):
        return [self.tf.create(t).tokens() if isinstance(t, str) else list(t)
                for t in texts]

    def fit(self, texts: Sequence) -> "BagOfWordsVectorizer":
        self.vocab = build_vocab(self._tokens(texts), min_count=self.min_count)
        return self

    def transform(self, texts: Sequence) -> np.ndarray:
        out = np.zeros((len(texts), len(self.vocab)), np.float32)
        for r, toks in enumerate(self._tokens(texts)):
            for t in toks:
                i = self.vocab.index_of(t)
                if i >= 0:
                    out[r, i] += 1
        return out

    def fit_transform(self, texts: Sequence) -> np.ndarray:
        return self.fit(texts).transform(texts)


class TfidfVectorizer(BagOfWordsVectorizer):
    """Reference: `bagofwords/vectorizer/TfidfVectorizer` (tf · log(N/df))."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.idf: Optional[np.ndarray] = None

    def fit(self, texts: Sequence) -> "TfidfVectorizer":
        toks = self._tokens(texts)
        self.vocab = build_vocab(toks, min_count=self.min_count)
        df = np.zeros(len(self.vocab), np.float64)
        for t in toks:
            for i in {self.vocab.index_of(w) for w in t}:
                if i >= 0:
                    df[i] += 1
        self.idf = np.log((1 + len(texts)) / (1 + df)).astype(np.float32) + 1
        return self

    def transform(self, texts: Sequence) -> np.ndarray:
        counts = super().transform(texts)
        tf = counts / np.maximum(counts.sum(axis=1, keepdims=True), 1)
        return tf * self.idf
