"""ParagraphVectors (doc2vec): DM and DBOW.

Reference parity: `models/paragraphvectors/ParagraphVectors.java` (1,439
LoC) with sequence learning algorithms `impl/sequence/{DM,DBOW}.java` —
document/label vectors trained jointly with (DM) or instead of (DBOW) word
context, plus `inferVector` for unseen documents (gradient steps on a fresh
doc vector with frozen word tables).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import build_vocab, unigram_table
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, _as_token_lists


@jax.jit
def _infer_step_dm(dv, syn0, syn1, targets, negs, lr_):
    """One inferVector gradient step, DM flavour (doc + word context).
    Module-level so repeated infer_vector() calls share one jit cache
    entry per (len(idx), negative) shape instead of re-tracing."""
    def loss_fn(v):
        h = 0.5 * (v[None, :] + syn0[targets])
        pos = jnp.einsum("bd,bd->b", h, syn1[targets])
        neg = jnp.einsum("bd,bkd->bk", h, syn1[negs])
        # SUM: per-pair SGD semantics (see word2vec.py)
        return (jnp.sum(jax.nn.softplus(-pos))
                + jnp.sum(jax.nn.softplus(neg)))

    return dv - lr_ * jax.grad(loss_fn)(dv)


@jax.jit
def _infer_step_dbow(dv, syn0, syn1, targets, negs, lr_):
    """DBOW flavour: the doc vector alone predicts each target (syn0 is
    unused and DCE'd; the signature matches _infer_step_dm so callers
    dispatch on self.dm only)."""
    def loss_fn(v):
        h = jnp.broadcast_to(v, (targets.shape[0], v.shape[0]))
        pos = jnp.einsum("bd,bd->b", h, syn1[targets])
        neg = jnp.einsum("bd,bkd->bk", h, syn1[negs])
        # SUM: per-pair SGD semantics (see word2vec.py)
        return (jnp.sum(jax.nn.softplus(-pos))
                + jnp.sum(jax.nn.softplus(neg)))

    return dv - lr_ * jax.grad(loss_fn)(dv)


class ParagraphVectors(Word2Vec):
    def __init__(self, *, dm: bool = True, **kw):
        kw.setdefault("min_count", 1)
        super().__init__(**kw)
        self.dm = dm
        self.doc_vectors: Optional[np.ndarray] = None
        self.labels: List[str] = []

    # ------------------------------------------------------------ fitting
    def fit(self, documents: Union[Sequence[str], Sequence[Sequence[str]]],
            labels: Optional[Sequence[str]] = None) -> "ParagraphVectors":
        from deeplearning4j_tpu.nlp.documents import LabelAwareIterator

        if isinstance(documents, LabelAwareIterator):
            # reference: PV.Builder.iterate(LabelAwareIterator) — documents
            # carry their own labels (LabelsSource-backed)
            labelled = list(documents)
            labels = [d.label for d in labelled]
            documents = [d.content for d in labelled]
        docs = _as_token_lists(documents, self.tokenizer_factory)
        raw_labels = [
            (labels[i] if labels is not None and labels[i] is not None
             else f"DOC_{i}")
            for i in range(len(docs))]
        # One TRAINED VECTOR PER LABEL (reference semantics): repeated
        # labels share a vector, trained on all their documents' windows.
        self.labels = list(dict.fromkeys(raw_labels))
        label_ids = np.array([self.labels.index(l) for l in raw_labels],
                             dtype=np.int64)
        self.vocab = build_vocab(docs, min_count=self.min_count)
        V, D, N = len(self.vocab), self.layer_size, len(self.labels)
        rng = np.random.default_rng(self.seed)
        params = {
            "syn0": jnp.asarray((rng.random((V, D), dtype=np.float32) - .5) / D),
            "syn1": jnp.zeros((V, D), jnp.float32),
            "docs": jnp.asarray((rng.random((N, D), dtype=np.float32) - .5) / D),
        }
        idx_docs = [
            np.array([self.vocab.index_of(w) for w in s], dtype=np.int64)
            for s in docs
        ]
        idx_docs = [s[s >= 0] for s in idx_docs]
        probs = unigram_table(self.vocab)
        step = self._make_pv_step()

        pairs = []  # (label_id, center, context)
        for d, s in enumerate(idx_docs):
            n = len(s)
            if n < 2:
                continue
            lid = label_ids[d]
            b = rng.integers(1, self.window + 1, n)
            for off in range(1, self.window + 1):
                if n <= off:
                    break
                i = np.arange(n - off)
                m = b[i + off] >= off
                pairs.append(np.stack([np.full(m.sum(), lid), s[i + off][m],
                                       s[i][m]], 1))
                m = b[i] >= off
                pairs.append(np.stack([np.full(m.sum(), lid), s[i][m],
                                       s[i + off][m]], 1))
        all_pairs = np.concatenate(pairs) if pairs else np.zeros((0, 3), np.int64)

        for epoch in range(self.epochs):
            order = rng.permutation(len(all_pairs))
            shuffled = all_pairs[order]
            frac_base = epoch / max(self.epochs, 1)
            for lo in range(0, len(shuffled), self.batch_size):
                chunk = shuffled[lo:lo + self.batch_size]
                if len(chunk) < 8:
                    continue
                negs = rng.choice(len(probs),
                                  size=(len(chunk), self.negative), p=probs)
                lr = max(self.lr * (1 - frac_base), self.min_lr)
                params = step(params, jnp.asarray(chunk[:, 0]),
                              jnp.asarray(chunk[:, 1]),
                              jnp.asarray(chunk[:, 2]),
                              jnp.asarray(negs),
                              jnp.asarray(lr, jnp.float32))
        self.syn0 = np.asarray(params["syn0"])
        self._syn1 = np.asarray(params["syn1"])
        self.doc_vectors = np.asarray(params["docs"])
        return self

    def _make_pv_step(self):
        dm = self.dm

        @jax.jit
        # graft: allow(GL102): factory runs once per fit(); the trainer
        # caches the returned jitted step for the whole epoch loop
        def step(params, doc_ids, centers, contexts, negatives, lr):
            def loss_fn(p):
                dv = p["docs"][doc_ids]            # [B,D]
                if dm:
                    h = 0.5 * (dv + p["syn0"][centers])   # DM: doc + word ctx
                else:
                    h = dv                                  # DBOW: doc only
                pos = jnp.einsum("bd,bd->b", h, p["syn1"][contexts])
                neg = jnp.einsum("bd,bkd->bk", h, p["syn1"][negatives])
                # SUM: per-pair SGD semantics (see word2vec.py)
                return (jnp.sum(jax.nn.softplus(-pos))
                        + jnp.sum(jax.nn.softplus(neg)))

            grads = jax.grad(loss_fn)(params)
            return jax.tree_util.tree_map(lambda a, g: a - lr * g,
                                          params, grads)

        return step

    # ------------------------------------------------------------ queries
    def doc_vector(self, label: str) -> Optional[np.ndarray]:
        try:
            return self.doc_vectors[self.labels.index(label)]
        except ValueError:
            return None

    def similarity_to_label(self, doc_a: str, doc_b: str) -> float:
        va, vb = self.doc_vector(doc_a), self.doc_vector(doc_b)
        if va is None or vb is None:
            return float("nan")
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def infer_vector(self, text: Union[str, Sequence[str]], *,
                     steps: int = 50, lr: float = 0.05) -> np.ndarray:
        """Reference: `ParagraphVectors.inferVector` — gradient-fit a fresh
        doc vector against frozen word tables."""
        tokens = (self.tokenizer_factory.create(text).tokens()
                  if isinstance(text, str) else list(text))
        idx = np.array([self.vocab.index_of(w) for w in tokens])
        idx = idx[idx >= 0]
        if len(idx) == 0:
            return np.zeros(self.layer_size, np.float32)
        rng = np.random.default_rng(self.seed)
        dv = jnp.asarray((rng.random(self.layer_size,
                                     dtype=np.float32) - .5) / self.layer_size)
        syn0 = jnp.asarray(self.syn0)
        syn1 = jnp.asarray(self._syn1)
        probs = unigram_table(self.vocab)
        targets = jnp.asarray(idx)
        step_fn = _infer_step_dm if self.dm else _infer_step_dbow

        for s in range(steps):
            negs = rng.choice(len(probs), size=(len(idx), self.negative),
                              p=probs)
            dv = step_fn(dv, syn0, syn1, targets, jnp.asarray(negs),
                         jnp.asarray(lr * (1 - s / steps), jnp.float32))
        return np.asarray(dv)
