"""CJK tokenizer factories — language-pack parity.

Reference parity: sibling modules `deeplearning4j-nlp-{japanese,chinese,
korean}` (SURVEY §2.5) bundle heavyweight analyzers (a kuromoji fork for
ja, ansj for zh, a Korean twitter-text port). Those are dictionary-driven
morphological analyzers; shipping ~55 files of dictionary machinery is not
what the TPU port needs, so these factories implement the standard
lightweight equivalents:

- Japanese + Chinese: min-cost LATTICE segmentation (`LatticeSegmenter`,
  the kuromoji/ansj algorithm core — Viterbi over dictionary + unknown
  nodes, beating greedy longest-match on ambiguous spans like 研究生命),
  seeded with small embedded high-frequency lexicons (JA_COMMON /
  ZH_COMMON) and extended by user dictionaries (words or word→cost).
  Japanese groups OOV same-script runs (katakana loanwords stay one
  token); Chinese degrades to unigram characters on OOV spans, like the
  reference's ansj fallback (`base_lexicon=()` for pure unigrams).
- Korean: whitespace-delimited eojeol, optionally stripped of trailing
  particles (josa) from a small closed set.

All three plug into the same `TokenizerFactory` SPI as the default
tokenizer (reference seam: `tokenization/tokenizerfactory/`), so
Word2Vec/ParagraphVectors/BagOfWords accept them unchanged.
"""

from __future__ import annotations

import unicodedata
from typing import Iterable, List, Optional

from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory


def _char_class(ch: str) -> str:
    code = ord(ch)
    if 0x4E00 <= code <= 0x9FFF or 0x3400 <= code <= 0x4DBF:
        return "kanji"
    if 0x3040 <= code <= 0x309F:
        return "hiragana"
    if 0x30A0 <= code <= 0x30FF or code == 0x30FC:
        return "katakana"
    if 0xAC00 <= code <= 0xD7AF:
        return "hangul"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "other"


def _runs(text: str) -> List[str]:
    out: List[str] = []
    cur, cls = "", None
    for ch in text:
        c = _char_class(ch)
        if c == cls and c not in ("space", "other"):
            cur += ch
        else:
            if cur:
                out.append(cur)
            cur = ch if c not in ("space",) else ""
            cls = c
            if c == "other" and cur:
                out.append(cur)
                cur = ""
    if cur:
        out.append(cur)
    return out


class LatticeSegmenter:
    """Min-cost lattice segmentation — the algorithmic core of the
    reference's kuromoji/ansj analyzers: build a lattice of dictionary
    entries + unknown-word nodes over the text and take the Viterbi
    (min total cost) path, instead of greedy longest-match (which
    mis-segments e.g. 研究生命 as 研究生|命 when 研究|生命 is cheaper).

    `lexicon`: word → cost (lower = preferred). Plain iterables get a
    default cost of `word_cost_base - word_cost_len * len(word)` so longer
    dictionary words win unless explicit costs say otherwise. Unknown
    characters cost `unk_cost` each, with a discount when they extend a
    same-character-class run (kuromoji's unknown-word grouping)."""

    def __init__(self, lexicon, *, unk_cost: float = 10.0,
                 unk_run_cost: float = 6.0,
                 word_cost_base: float = 8.0, word_cost_len: float = 3.0):
        if isinstance(lexicon, dict):
            self.costs = {w: float(c) for w, c in lexicon.items()}
        else:
            self.costs = {
                w: max(word_cost_base - word_cost_len * len(w), 1.0)
                for w in (lexicon or ())}
        self.max_len = max((len(w) for w in self.costs), default=1)
        self.unk_cost = unk_cost
        self.unk_run_cost = unk_run_cost

    def segment(self, text: str) -> List[str]:
        n = len(text)
        INF = float("inf")
        best = [INF] * (n + 1)
        back: List[Optional[int]] = [None] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] == INF:
                continue
            # dictionary edges
            for ln in range(1, min(self.max_len, n - i) + 1):
                w = text[i:i + ln]
                c = self.costs.get(w)
                if c is not None and best[i] + c < best[i + ln]:
                    best[i + ln] = best[i] + c
                    back[i + ln] = i
            # unknown single char; cheaper when continuing a same-class run
            # (so an OOV katakana loanword or digit string stays one token)
            cont = (i > 0 and back[i] == i - 1
                    and _char_class(text[i]) == _char_class(text[i - 1]))
            c = self.unk_run_cost if cont else self.unk_cost
            if best[i] + c < best[i + 1]:
                best[i + 1] = best[i] + c
                back[i + 1] = i
        # reconstruct, merging adjacent same-class unknown chars into runs
        cuts = []
        j = n
        while j > 0:
            cuts.append(j)
            j = back[j]
        cuts.append(0)
        cuts.reverse()
        pieces = [text[a:b] for a, b in zip(cuts, cuts[1:])]
        if self.unk_run_cost >= self.unk_cost:
            return pieces   # run-grouping disabled: unknowns stay unigram
        out: List[str] = []
        for p in pieces:
            if (out and len(p) == 1
                    and out[-1] not in self.costs and p not in self.costs
                    and _char_class(p) == _char_class(out[-1][-1])):
                out[-1] += p
            else:
                out.append(p)
        return out


# Small embedded starter lexicons (high-frequency words/particles) so the
# factories are useful out of the box; user dictionaries extend/override.
# The reference ships full analyzer dictionaries (~MBs); these cover the
# closed-class core the segmentation quality hinges on.
ZH_COMMON = (
    "的 了 是 在 不 我 有 他 这 中 大 来 上 国 个 到 说 们 为 子 和 你 地 出 道 "
    "也 时 年 得 就 那 要 下 以 生 会 自 着 去 之 过 家 学 对 可 她 里 后 小 么 "
    "我们 你们 他们 她们 这个 那个 什么 没有 知道 现在 时候 自己 大家 因为 "
    "所以 但是 可以 已经 还是 如果 虽然 时间 问题 工作 学习 学生 老师 朋友 "
    "中国 北京 研究 生命 科学 技术 经济 发展 社会 世界 国家 政府 人民 "
    "今天 明天 昨天 东西 地方 事情 开始 结束 喜欢 觉得 认为 希望 需要 "
    "音乐 电影"
).split()

JA_COMMON = (
    "の は が を に で と も か ら な だ です ます した する いる ある なる "
    "これ それ あれ この その あの ここ そこ どこ わたし あなた かれ かのじょ "
    "こと もの とき ひと 私 僕 彼 彼女 日本 東京 学生 先生 学校 会社 仕事 "
    "時間 今日 明日 昨日 毎日 今 年 月 日 人 何 言葉 勉強 研究 世界 国 家族 "
    "友達 ありがとう こんにちは さようなら ください から まで より など "
    "について"
).split()


def _build_lexicon(base_words, user) -> dict:
    """base + user lexicon merge with one shared cost formula (user words
    cost slightly less, so they beat the embedded core at equal length)."""
    def cost(w, base, floor):
        return max(base - 3.0 * len(w), floor)

    lex = {w: cost(w, 8.0, 1.0) for w in base_words}
    if isinstance(user, dict):
        lex.update({w: float(c) for w, c in user.items()})
    else:
        lex.update({w: cost(w, 7.0, 0.5) for w in (user or ())})
    return lex


def _spans(text: str, classes) -> List:
    """Partition into (is_target, span) with CONSECUTIVE target-class runs
    coalesced — Japanese words cross script boundaries (kanji+okurigana
    like 食べる), so the lattice must see the whole CJK span."""
    out: List = []
    for run in _runs(text):
        tgt = _char_class(run[0]) in classes
        if out and out[-1][0] and tgt:
            out[-1] = (True, out[-1][1] + run)
        else:
            out.append((tgt, run))
    return out


class JapaneseTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-japanese` (kuromoji fork) — same
    algorithm class: min-cost lattice segmentation over a lexicon
    (LatticeSegmenter) seeded with the embedded JA_COMMON core; a user
    dictionary (iterable of words or word→cost dict) extends it."""

    _CJK = ("kanji", "hiragana", "katakana")

    def __init__(self, user_dictionary: Optional[Iterable[str]] = None, *,
                 base_lexicon: Optional[Iterable[str]] = None):
        super().__init__()
        base = JA_COMMON if base_lexicon is None else base_lexicon
        self._seg = LatticeSegmenter(_build_lexicon(base, user_dictionary))

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for is_cjk, span in _spans(unicodedata.normalize("NFKC", text),
                                   self._CJK):
            if is_cjk:
                toks.extend(self._seg.segment(span))
            else:
                toks.append(span)
        return _ListTokenizer(toks, self._pre)


class ChineseTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-chinese` (ansj analyzer) — min-cost
    lattice segmentation (ZH_COMMON core + user dictionary); degrades to
    unigram characters on fully-OOV spans like the reference."""

    def __init__(self, dictionary: Optional[Iterable[str]] = None, *,
                 base_lexicon: Optional[Iterable[str]] = None):
        super().__init__()
        base = ZH_COMMON if base_lexicon is None else base_lexicon
        # Chinese unknowns should NOT merge into runs (OOV hanzi stay
        # unigrams — the ansj fallback); a run discount would glue them.
        self._seg = LatticeSegmenter(_build_lexicon(base, dictionary),
                                     unk_run_cost=10.0)

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for is_hanzi, span in _spans(unicodedata.normalize("NFKC", text),
                                     ("kanji",)):
            if is_hanzi:
                toks.extend(self._seg.segment(span))
            else:
                toks.append(span)
        return _ListTokenizer(toks, self._pre)


_JOSA = ("은", "는", "이", "가", "을", "를", "의", "에", "에서", "으로",
         "로", "와", "과", "도", "만", "까지", "부터", "에게")


class KoreanTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-korean` (twitter-text port)."""

    def __init__(self, strip_particles: bool = True):
        super().__init__()
        self.strip_particles = strip_particles

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for word in text.split():
            w = word.strip(".,!?…·()[]\"'")
            if not w:
                continue
            if self.strip_particles and _char_class(w[-1]) == "hangul":
                for josa in sorted(_JOSA, key=len, reverse=True):
                    if len(w) > len(josa) and w.endswith(josa):
                        w = w[:-len(josa)]
                        break
            toks.append(w)
        return _ListTokenizer(toks, self._pre)


class _ListTokenizer(Tokenizer):
    """Tokenizer over a precomputed token list (factories above)."""

    def __init__(self, toks: List[str], pre):
        self._toks = toks
        self._pre = pre

    def tokens(self) -> List[str]:
        out = [self._pre.pre_process(t) if self._pre else t
               for t in self._toks]
        return [t for t in out if t]


# --------------------------------------------------------------------------
# Japanese morphological analysis (POS + readings + base forms)
# --------------------------------------------------------------------------
import dataclasses as _dc


@_dc.dataclass(frozen=True)
class Morpheme:
    """One analyzed token — kuromoji Token analogue (reference:
    deeplearning4j-nlp-japanese bundles a kuromoji fork whose Token
    carries surface / part-of-speech / reading / base form)."""

    surface: str
    pos: str                      # kuromoji-style main category (動詞 etc.)
    reading: Optional[str] = None   # katakana
    base: Optional[str] = None      # dictionary (base) form


def _hira_to_kata(s: str) -> str:
    return "".join(chr(ord(c) + 0x60) if 0x3041 <= ord(c) <= 0x3096 else c
                   for c in s)


# surface -> (POS, katakana reading). Closed-class core + common verbs
# (verbs carry a conjugation class for inflection generation below:
# "1" ichidan, "5" godan, "irr" irregular).
JA_MORPH: dict = {}
for _w, _r in (("の", "ノ"), ("は", "ハ"), ("が", "ガ"), ("を", "ヲ"),
               ("に", "ニ"), ("で", "デ"), ("と", "ト"), ("も", "モ"),
               ("か", "カ"), ("から", "カラ"), ("まで", "マデ"),
               ("より", "ヨリ"), ("など", "ナド"), ("について", "ニツイテ")):
    JA_MORPH[_w] = ("助詞", _r)
for _w, _r in (("です", "デス"), ("ます", "マス"), ("ました", "マシタ"),
               ("ません", "マセン"), ("た", "タ"), ("だ", "ダ"),
               ("ない", "ナイ"), ("な", "ナ"), ("ら", "ラ")):
    JA_MORPH[_w] = ("助動詞", _r)
for _w, _r in (("これ", "コレ"), ("それ", "ソレ"), ("あれ", "アレ"),
               ("ここ", "ココ"), ("そこ", "ソコ"), ("どこ", "ドコ"),
               ("わたし", "ワタシ"), ("あなた", "アナタ"), ("私", "ワタシ"),
               ("僕", "ボク"), ("彼", "カレ"), ("彼女", "カノジョ"),
               ("かれ", "カレ"), ("かのじょ", "カノジョ")):
    JA_MORPH[_w] = ("代名詞", _r)
for _w, _r in (("日本", "ニホン"), ("東京", "トウキョウ"),
               ("学生", "ガクセイ"), ("先生", "センセイ"),
               ("学校", "ガッコウ"), ("会社", "カイシャ"),
               ("仕事", "シゴト"), ("時間", "ジカン"), ("今日", "キョウ"),
               ("明日", "アシタ"), ("昨日", "キノウ"), ("毎日", "マイニチ"),
               ("今", "イマ"),
               ("年", "トシ"), ("月", "ツキ"), ("日", "ヒ"), ("人", "ヒト"),
               ("何", "ナニ"), ("言葉", "コトバ"), ("勉強", "ベンキョウ"),
               ("研究", "ケンキュウ"), ("世界", "セカイ"), ("国", "クニ"),
               ("家族", "カゾク"), ("友達", "トモダチ"), ("こと", "コト"),
               ("もの", "モノ"), ("とき", "トキ"), ("ひと", "ヒト")):
    JA_MORPH[_w] = ("名詞", _r)
for _w, _r in (("ありがとう", "アリガトウ"), ("こんにちは", "コンニチハ"),
               ("さようなら", "サヨウナラ")):
    JA_MORPH[_w] = ("感動詞", _r)
JA_MORPH["ください"] = ("動詞", "クダサイ")

# verb dictionary: base form -> (reading, conjugation class)
JA_VERBS = {
    "する": ("スル", "irr"), "いる": ("イル", "1"), "ある": ("アル", "5"),
    "なる": ("ナル", "5"), "食べる": ("タベル", "1"), "見る": ("ミル", "1"),
    "行く": ("イク", "5"), "来る": ("クル", "irr"), "思う": ("オモウ", "5"),
    "言う": ("イウ", "5"), "分かる": ("ワカル", "5"), "書く": ("カク", "5"),
    "読む": ("ヨム", "5"), "話す": ("ハナス", "5"), "使う": ("ツカウ", "5"),
    "作る": ("ツクル", "5"), "持つ": ("モツ", "5"), "出る": ("デル", "1"),
    "入る": ("ハイル", "5"), "待つ": ("マツ", "5"), "買う": ("カウ", "5"),
    "飲む": ("ノム", "5"), "泳ぐ": ("オヨグ", "5"), "死ぬ": ("シヌ", "5"),
    "遊ぶ": ("アソブ", "5"), "休む": ("ヤスム", "5"),
}

# godan final-kana -> (masu-stem kana, ta/te euphonic past, negative stem)
_GODAN = {
    "う": ("い", "った", "わ"), "つ": ("ち", "った", "た"),
    "る": ("り", "った", "ら"), "む": ("み", "んだ", "ま"),
    "ぶ": ("び", "んだ", "ば"), "ぬ": ("に", "んだ", "な"),
    "く": ("き", "いた", "か"), "ぐ": ("ぎ", "いだ", "が"),
    "す": ("し", "した", "さ"),
}


def _inflections(base: str, reading: str, klass: str):
    """Generate common inflected (surface, reading) pairs for one verb.

    Regular verbs substitute only the FINAL kana, so readings follow the
    same substitution on the base reading. Irregular verbs (する/来る)
    carry explicit stem readings — 来る's stem kanji reads ク only in the
    dictionary form (来た=キタ, 来ない=コナイ), which no suffix rule can
    derive."""
    rstem = reading[:-1]              # reading minus the final ル/ウ row kana
    if klass == "irr":
        stems = {"する": (("し", "シ"), ("した", "シタ"), ("し", "シ")),
                 "来る": (("来", "キ"), ("来た", "キタ"), ("来", "コ"))}
        (stem, stem_r), (past, past_r), (neg, neg_r) = stems[base]
    elif klass == "1":
        stem, stem_r = base[:-1], rstem
        past, past_r = stem + "た", rstem + "タ"
        neg, neg_r = stem, rstem
    else:
        k = base[-1]
        ms, pa, ns = _GODAN[k]
        stem, stem_r = base[:-1] + ms, rstem + _hira_to_kata(ms)
        past, past_r = base[:-1] + pa, rstem + _hira_to_kata(pa)
        neg, neg_r = base[:-1] + ns, rstem + _hira_to_kata(ns)
        if base == "行く":        # the one godan euphonic exception
            past, past_r = "行った", "イッタ"
    yield base, reading
    yield past, past_r                            # plain past
    te = "で" if past.endswith("だ") else "て"
    yield past[:-1] + te, past_r[:-1] + _hira_to_kata(te)   # te-form
    for suf in ("ます", "ました", "ません", "ましょう"):
        yield stem + suf, stem_r + _hira_to_kata(suf)       # polite row
    yield neg + "ない", neg_r + "ナイ"            # plain negative


# inflected surface -> (base form, reading) — built once
JA_INFLECTED = {}
for _b, (_r, _k) in JA_VERBS.items():
    for _surf, _read in _inflections(_b, _r, _k):
        JA_INFLECTED.setdefault(_surf, (_b, _read))


class JapaneseMorphologicalAnalyzer:
    """kuromoji-capability analogue: segment + POS-tag + readings + base
    forms. Segmentation is the same min-cost lattice as
    JapaneseTokenizerFactory, with the verb dictionary's generated
    inflected surfaces added so conjugated verbs stay one token
    (kuromoji's dictionary stores inflected entries the same way)."""

    def __init__(self, user_dictionary=None):
        words = dict(_build_lexicon(JA_COMMON, user_dictionary))
        for surf in JA_INFLECTED:
            words.setdefault(surf, max(8.0 - 3.0 * len(surf), 0.4))
        self._seg = LatticeSegmenter(words)

    def analyze(self, text: str) -> List[Morpheme]:
        # same NFKC normalization as JapaneseTokenizerFactory.create, so
        # half-width katakana / full-width latin take the same path
        text = unicodedata.normalize("NFKC", text)
        out: List[Morpheme] = []
        for is_cjk, span in _spans(text, ("kanji", "hiragana", "katakana")):
            if not is_cjk:
                for tok in span.split():
                    if tok:
                        out.append(Morpheme(
                            tok, "名詞" if tok[0].isalnum() else "記号"))
                continue
            for tok in self._seg.segment(span):
                out.append(self._morpheme(tok))
        return out

    def _morpheme(self, tok: str) -> Morpheme:
        if tok in JA_INFLECTED:
            base, reading = JA_INFLECTED[tok]
            return Morpheme(tok, "動詞", reading, base)
        if tok in JA_MORPH:
            pos, reading = JA_MORPH[tok]
            return Morpheme(tok, pos, reading, tok)
        cls = _char_class(tok[0])
        if cls == "katakana":
            return Morpheme(tok, "名詞", tok, tok)
        if cls == "hiragana":
            return Morpheme(tok, "助詞", _hira_to_kata(tok), tok)
        return Morpheme(tok, "名詞", None, tok)   # unknown kanji


# --------------------------------------------------------------------------
# Korean morphological analysis (stem/josa/eomi decomposition + POS)
# --------------------------------------------------------------------------
# Reference: `deeplearning4j-nlp-korean/.../KoreanTokenizer.java:34` wraps
# twitter-korean-text, whose tokenizer is MORPHOLOGY-based: each eojeol
# (space unit) decomposes into stem + particle (josa) / verb ending
# (eomi), tagged with KoreanPos (Noun, Verb, Adjective, Josa, Eomi,
# Number, Foreign, Punctuation, ...), with conjugated verbs recovered to
# their dictionary form. Same capability here on an embedded dictionary
# core (like JA above): jamo-aware de-conjugation handles the 았/었
# contraction (가+았→갔, 하+았→했, ...) arithmetically.

_HANGUL_BASE = 0xAC00
_JUNGSEONG = 21
_JONGSEONG = 28
# jongseong (final consonant) index of ㅆ in the syllable formula
_JONG_SS = 20


def _hangul_decompose(ch: str):
    """Syllable -> (initial, vowel, final) indices, or None."""
    code = ord(ch) - _HANGUL_BASE
    if not 0 <= code < 11172:
        return None
    return (code // (_JUNGSEONG * _JONGSEONG),
            (code // _JONGSEONG) % _JUNGSEONG,
            code % _JONGSEONG)


def _hangul_compose(ini: int, vow: int, fin: int) -> str:
    return chr(_HANGUL_BASE + (ini * _JUNGSEONG + vow) * _JONGSEONG + fin)


# contracted-syllable vowel -> [(stem vowel, 았/었), ...] candidates:
# the ㅆ-final syllable's vowel encodes which stem vowel absorbed the
# 아/어 row.  가+았→갔 (ㅏ→ㅏ), 하+였→했 (ㅐ→ㅏ irregular), 오+았→왔 /
# 보+았→봤 (ㅘ→ㅗ), 주+었→줬 (ㅝ→ㅜ), 되+었→됐 (ㅙ→ㅚ), 마시+었→마셨
# (ㅕ→ㅣ), 서+었→섰 (ㅓ→ㅓ). Multiple candidates (e.g. ㅐ could be a
# genuine ㅐ stem) are all tried against the stem dictionary.
_PAST_BY_VOWEL = {
    0: [(0, "았")],               # ㅏ
    1: [(0, "았"), (1, "었")],    # ㅐ: 하-irregular first, ㅐ stems second
    9: [(8, "았")],               # ㅘ -> ㅗ
    4: [(4, "었")],               # ㅓ
    14: [(13, "었")],             # ㅝ -> ㅜ
    10: [(11, "었")],             # ㅙ -> ㅚ
    6: [(20, "었")],              # ㅕ -> ㅣ
    20: [(20, "었")],             # ㅣ
}

KO_NOUNS = set(
    "학교 학생 선생님 친구 사람 시간 오늘 내일 어제 한국 서울 책 물 밥 집 "
    "회사 일 말 나라 세계 문제 공부 연구 영화 음식 음악 아침 저녁 점심 "
    "이름 생각 마음 이야기 가족 아버지 어머니 동생 언니 형 누나".split())
KO_PRONOUNS = set("나 너 저 우리 그 그녀 누구 무엇 이것 그것 저것".split())
KO_ADVERBS = set("매우 아주 너무 잘 못 더 다시 같이 빨리 천천히 많이".split())
# verb/adjective STEMS -> (dictionary form, pos)
KO_STEMS = {}
for _stem in "가 오 하 먹 보 있 없 되 주 받 만나 사 배우 읽 듣 마시 만들".split():
    KO_STEMS[_stem] = (_stem + "다", "Verb")
for _stem in "좋 크 작 예쁘 많 적 높 낮 길 짧".split():
    KO_STEMS[_stem] = (_stem + "다", "Adjective")
for _stem in "좋아하 공부하 일하 사랑하 말하 생각하".split():
    KO_STEMS[_stem] = (_stem + "다", "Verb")

# verb endings (eomi), matched longest-first AFTER de-contraction
_KO_EOMI = ("습니다", "ㅂ니다", "었습니다", "았습니다", "어요", "아요",
            "었어요", "았어요", "었다", "았다", "는다", "ㄴ다", "지만",
            "어서", "아서", "으면", "고", "면", "게", "기", "며", "다")
_KO_EOMI_BY_LEN = tuple(sorted(_KO_EOMI, key=len, reverse=True))
_JOSA_BY_LEN = tuple(sorted(_JOSA, key=len, reverse=True))


@_dc.dataclass(frozen=True)
class KoMorpheme:
    """twitter-korean-text KoreanToken analogue: surface + KoreanPos tag
    + dictionary base form for inflected stems."""

    surface: str
    pos: str                      # Noun/Verb/Adjective/Josa/Eomi/...
    base: Optional[str] = None    # 가 -> 가다 for verb/adjective stems


class KoreanMorphologicalAnalyzer:
    """Morphology-based Korean analysis (the reference tokenizer's
    capability): eojeol -> stem + josa / eomi with POS tags and
    de-conjugated dictionary forms."""

    def __init__(self, user_nouns=None):
        self.nouns = set(KO_NOUNS)
        if user_nouns:
            self.nouns.update(user_nouns)

    # ---- de-contraction: expand 갔 -> 가았, 왔 -> 오았, 했 -> 하았 ----
    @staticmethod
    def _expand_past(word: str) -> List[str]:
        out: List[str] = []
        for i, ch in enumerate(word):
            d = _hangul_decompose(ch)
            if d is None or d[2] != _JONG_SS:
                continue
            ini, vow, _ = d
            for stem_vow, past in _PAST_BY_VOWEL.get(vow, ()):
                stem_ch = _hangul_compose(ini, stem_vow, 0)
                out.append(word[:i] + stem_ch + past + word[i + 1:])
        return out

    # batchim-contracted eomi: the ending's initial consonant fuses into
    # the stem's final open syllable as a jongseong — 배우+ㄴ다→배운다,
    # 일하+ㅂ니다→일합니다. Decompose arithmetically like the past
    # contraction: (jongseong index, compatibility-jamo ending prefix).
    _BATCHIM_EOMI = ((4, "ㄴ"), (17, "ㅂ"))   # ㄴ(는다 row), ㅂ(니다 row)

    @classmethod
    def _expand_batchim(cls, word: str) -> List[str]:
        out: List[str] = []
        for i, ch in enumerate(word):
            d = _hangul_decompose(ch)
            if d is None:
                continue
            ini, vow, fin = d
            for jong, jamo in cls._BATCHIM_EOMI:
                if fin == jong:
                    out.append(word[:i] + _hangul_compose(ini, vow, 0)
                               + jamo + word[i + 1:])
        return out

    def _try_stem(self, w: str):
        """Match stem + eomi (after de-contraction); None if not verbal."""
        for cand in (w, *self._expand_past(w), *self._expand_batchim(w)):
            for eomi in _KO_EOMI_BY_LEN:
                if not cand.endswith(eomi) or len(cand) <= len(eomi):
                    continue
                stem = cand[:-len(eomi)]
                if stem in KO_STEMS:
                    base, pos = KO_STEMS[stem]
                    return [KoMorpheme(stem, pos, base),
                            KoMorpheme(eomi, "Eomi")]
        return None

    def _split_josa(self, w: str):
        for josa in _JOSA_BY_LEN:
            if len(w) > len(josa) and w.endswith(josa):
                return w[:-len(josa)], josa
        return w, None

    def analyze(self, text: str) -> List[KoMorpheme]:
        out: List[KoMorpheme] = []
        for word in text.split():
            core = word.strip(".,!?…·()[]\"'")
            at = word.find(core) if core else len(word)
            for ch in word[:at]:
                out.append(KoMorpheme(ch, "Punctuation"))
            if core:
                out.extend(self._analyze_word(core))
            for ch in word[at + len(core):]:
                out.append(KoMorpheme(ch, "Punctuation"))
        return out

    def _analyze_word(self, w: str) -> List[KoMorpheme]:
        if w.isdigit():
            return [KoMorpheme(w, "Number")]
        if all(_char_class(c) != "hangul" for c in w):
            return [KoMorpheme(w, "Foreign")]
        verbal = self._try_stem(w)
        if verbal is not None:
            return verbal
        # closed-class exact matches BEFORE the josa split: 같이 is the
        # adverb, not 같+이 (noun+josa)
        if w in KO_PRONOUNS:
            return [KoMorpheme(w, "Pronoun")]
        if w in KO_ADVERBS:
            return [KoMorpheme(w, "Adverb")]
        stem, josa = self._split_josa(w)
        if josa is not None:
            morphs = (self._try_stem(stem)
                      if stem not in self.nouns
                      and stem not in KO_PRONOUNS else None)
            if morphs is None:
                pos = "Pronoun" if stem in KO_PRONOUNS else "Noun"
                morphs = [KoMorpheme(stem, pos)]
            return morphs + [KoMorpheme(josa, "Josa")]
        return [KoMorpheme(w, "Noun")]


class KoreanMorphologicalTokenizerFactory(TokenizerFactory):
    """Tokenizer over the morphological analysis (the reference
    KoreanTokenizer emits every morpheme — stems AND particles — as
    tokens; `KoreanTokenizer.java:41-48`)."""

    def __init__(self, keep_particles: bool = False, user_nouns=None):
        super().__init__()
        self.keep_particles = keep_particles
        self._an = KoreanMorphologicalAnalyzer(user_nouns)

    def create(self, text: str) -> Tokenizer:
        toks = []
        for m in self._an.analyze(text):
            if m.pos == "Punctuation":
                continue
            if not self.keep_particles and m.pos in ("Josa", "Eomi"):
                continue
            toks.append(m.surface)
        return _ListTokenizer(toks, self._pre)


# --------------------------------------------------------------------------
# Chinese part-of-speech tagging (ansj nature analogue)
# --------------------------------------------------------------------------
# Reference: `deeplearning4j-nlp-chinese/.../ChineseTokenizer.java` wraps
# the ansj analyzer, whose terms carry a "nature" POS tag (n/v/a/d/r/
# m/q/p/c/u/w/en). Same tag alphabet here over the lattice segmentation.

_ZH_POS: dict = {}
for _w in "的 了 着 过 之 地 得 吗 呢 吧 啊".split():
    _ZH_POS[_w] = "u"        # particle (incl. sentence-final 吗/呢/吧/啊)
for _w in ("我 你 他 她 它 我们 你们 他们 她们 自己 大家 这 那 这个 那个 "
           "什么 谁").split():
    _ZH_POS[_w] = "r"        # pronoun
for _w in ("是 有 来 到 说 去 会 要 知道 喜欢 觉得 认为 希望 需要 学习 "
           "工作 研究 发展 开始 结束 出 可以 没有 听 看 想 走 吃 喝 写 "
           "买 卖 读 用").split():
    _ZH_POS[_w] = "v"        # verb
for _w in "大 小 好 新 高 美 多 少 长 短 快 慢".split():
    _ZH_POS[_w] = "a"        # adjective
for _w in "很 也 就 都 不 还 已经 再 只 更 最".split():
    _ZH_POS[_w] = "d"        # adverb
for _w in "在 从 对 为 把 被 向 于 给".split():
    _ZH_POS[_w] = "p"        # preposition
for _w in "和 与 或 但是 因为 所以 如果 虽然 而且".split():
    _ZH_POS[_w] = "c"        # conjunction
for _w in "个 只 本 张 条 件 位 次 种 年 岁".split():
    _ZH_POS[_w] = "q"        # measure word (incl. time-quantity 年/岁)
for _w in "一 二 三 四 五 六 七 八 九 十 百 千 万 亿 两".split():
    _ZH_POS[_w] = "m"        # numeral


@_dc.dataclass(frozen=True)
class ZhTerm:
    """ansj Term analogue: surface + nature (POS) tag."""

    surface: str
    nature: str


class ChineseMorphologicalAnalyzer:
    """Segmentation + ansj-style nature tagging: dictionary tags for the
    closed classes, digit/latin/punct detection, noun default (ansj's
    unknown-word behavior)."""

    def __init__(self, dictionary=None, user_pos=None):
        self._factory = ChineseTokenizerFactory(dictionary)
        self._pos = dict(_ZH_POS)
        if user_pos:
            self._pos.update(user_pos)

    def analyze(self, text: str) -> List[ZhTerm]:
        out: List[ZhTerm] = []
        for tok in self._factory.create(text).tokens():
            for piece in tok.split():
                out.append(ZhTerm(piece, self._tag(piece)))
        return out

    def _tag(self, w: str) -> str:
        if w in self._pos:
            return self._pos[w]
        if any(c.isdigit() for c in w) and all(
                c in "0123456789.%" for c in w):
            return "m"
        if all(ord(c) < 128 for c in w):
            return "en" if w[0].isalpha() else "w"
        if all(not c.isalnum() for c in w):
            return "w"
        return "n"
