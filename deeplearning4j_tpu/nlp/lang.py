"""CJK tokenizer factories — language-pack parity.

Reference parity: sibling modules `deeplearning4j-nlp-{japanese,chinese,
korean}` (SURVEY §2.5) bundle heavyweight analyzers (a kuromoji fork for
ja, ansj for zh, a Korean twitter-text port). Those are dictionary-driven
morphological analyzers; shipping ~55 files of dictionary machinery is not
what the TPU port needs, so these factories implement the standard
lightweight equivalents:

- Japanese: character-class run segmentation (kanji / hiragana / katakana /
  latin / digit runs split at class boundaries) — the classic dictionary-
  free baseline; a user dictionary can refine it via longest-match.
- Chinese: greedy forward maximum-match over an optional user dictionary,
  falling back to unigram characters (the reference ansj default degrades
  the same way on OOV).
- Korean: whitespace-delimited eojeol, optionally stripped of trailing
  particles (josa) from a small closed set.

All three plug into the same `TokenizerFactory` SPI as the default
tokenizer (reference seam: `tokenization/tokenizerfactory/`), so
Word2Vec/ParagraphVectors/BagOfWords accept them unchanged.
"""

from __future__ import annotations

import unicodedata
from typing import Iterable, List, Optional, Sequence, Set

from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory


def _char_class(ch: str) -> str:
    code = ord(ch)
    if 0x4E00 <= code <= 0x9FFF or 0x3400 <= code <= 0x4DBF:
        return "kanji"
    if 0x3040 <= code <= 0x309F:
        return "hiragana"
    if 0x30A0 <= code <= 0x30FF or code == 0x30FC:
        return "katakana"
    if 0xAC00 <= code <= 0xD7AF:
        return "hangul"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "other"


def _runs(text: str) -> List[str]:
    out: List[str] = []
    cur, cls = "", None
    for ch in text:
        c = _char_class(ch)
        if c == cls and c not in ("space", "other"):
            cur += ch
        else:
            if cur:
                out.append(cur)
            cur = ch if c not in ("space",) else ""
            cls = c
            if c == "other" and cur:
                out.append(cur)
                cur = ""
    if cur:
        out.append(cur)
    return out


def _max_match(text: str, dictionary: Set[str], max_len: int) -> List[str]:
    """Greedy forward longest-match; unmatched spans fall back per-char."""
    out: List[str] = []
    i = 0
    while i < len(text):
        match = None
        for ln in range(min(max_len, len(text) - i), 1, -1):
            if text[i:i + ln] in dictionary:
                match = text[i:i + ln]
                break
        if match:
            out.append(match)
            i += len(match)
        else:
            out.append(text[i])
            i += 1
    return out


class JapaneseTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-japanese` (kuromoji fork)."""

    def __init__(self, user_dictionary: Optional[Iterable[str]] = None):
        super().__init__()
        self._dict = set(user_dictionary or ())
        self._max = max((len(w) for w in self._dict), default=0)

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for run in _runs(unicodedata.normalize("NFKC", text)):
            cls = _char_class(run[0])
            if self._dict and cls in ("kanji", "hiragana", "katakana"):
                toks.extend(_max_match(run, self._dict, self._max))
            else:
                toks.append(run)
        return _ListTokenizer(toks, self._pre)


class ChineseTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-chinese` (ansj analyzer)."""

    def __init__(self, dictionary: Optional[Iterable[str]] = None):
        super().__init__()
        self._dict = set(dictionary or ())
        self._max = max((len(w) for w in self._dict), default=0)

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for run in _runs(unicodedata.normalize("NFKC", text)):
            if _char_class(run[0]) == "kanji":
                if self._dict:
                    toks.extend(_max_match(run, self._dict, self._max))
                else:
                    toks.extend(run)  # unigram fallback
            else:
                toks.append(run)
        return _ListTokenizer(toks, self._pre)


_JOSA = ("은", "는", "이", "가", "을", "를", "의", "에", "에서", "으로",
         "로", "와", "과", "도", "만", "까지", "부터", "에게")


class KoreanTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-korean` (twitter-text port)."""

    def __init__(self, strip_particles: bool = True):
        super().__init__()
        self.strip_particles = strip_particles

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for word in text.split():
            w = word.strip(".,!?…·()[]\"'")
            if not w:
                continue
            if self.strip_particles and _char_class(w[-1]) == "hangul":
                for josa in sorted(_JOSA, key=len, reverse=True):
                    if len(w) > len(josa) and w.endswith(josa):
                        w = w[:-len(josa)]
                        break
            toks.append(w)
        return _ListTokenizer(toks, self._pre)


class _ListTokenizer(Tokenizer):
    """Tokenizer over a precomputed token list (factories above)."""

    def __init__(self, toks: List[str], pre):
        self._toks = toks
        self._pre = pre

    def tokens(self) -> List[str]:
        out = [self._pre.pre_process(t) if self._pre else t
               for t in self._toks]
        return [t for t in out if t]
