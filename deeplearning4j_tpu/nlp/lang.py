"""CJK tokenizer factories — language-pack parity.

Reference parity: sibling modules `deeplearning4j-nlp-{japanese,chinese,
korean}` (SURVEY §2.5) bundle heavyweight analyzers (a kuromoji fork for
ja, ansj for zh, a Korean twitter-text port). Those are dictionary-driven
morphological analyzers; shipping ~55 files of dictionary machinery is not
what the TPU port needs, so these factories implement the standard
lightweight equivalents:

- Japanese + Chinese: min-cost LATTICE segmentation (`LatticeSegmenter`,
  the kuromoji/ansj algorithm core — Viterbi over dictionary + unknown
  nodes, beating greedy longest-match on ambiguous spans like 研究生命),
  seeded with small embedded high-frequency lexicons (JA_COMMON /
  ZH_COMMON) and extended by user dictionaries (words or word→cost).
  Japanese groups OOV same-script runs (katakana loanwords stay one
  token); Chinese degrades to unigram characters on OOV spans, like the
  reference's ansj fallback (`base_lexicon=()` for pure unigrams).
- Korean: whitespace-delimited eojeol, optionally stripped of trailing
  particles (josa) from a small closed set.

All three plug into the same `TokenizerFactory` SPI as the default
tokenizer (reference seam: `tokenization/tokenizerfactory/`), so
Word2Vec/ParagraphVectors/BagOfWords accept them unchanged.
"""

from __future__ import annotations

import unicodedata
from typing import Iterable, List, Optional

from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory


def _char_class(ch: str) -> str:
    code = ord(ch)
    if 0x4E00 <= code <= 0x9FFF or 0x3400 <= code <= 0x4DBF:
        return "kanji"
    if 0x3040 <= code <= 0x309F:
        return "hiragana"
    if 0x30A0 <= code <= 0x30FF or code == 0x30FC:
        return "katakana"
    if 0xAC00 <= code <= 0xD7AF:
        return "hangul"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "other"


def _runs(text: str) -> List[str]:
    out: List[str] = []
    cur, cls = "", None
    for ch in text:
        c = _char_class(ch)
        if c == cls and c not in ("space", "other"):
            cur += ch
        else:
            if cur:
                out.append(cur)
            cur = ch if c not in ("space",) else ""
            cls = c
            if c == "other" and cur:
                out.append(cur)
                cur = ""
    if cur:
        out.append(cur)
    return out


class LatticeSegmenter:
    """Min-cost lattice segmentation — the algorithmic core of the
    reference's kuromoji/ansj analyzers: build a lattice of dictionary
    entries + unknown-word nodes over the text and take the Viterbi
    (min total cost) path, instead of greedy longest-match (which
    mis-segments e.g. 研究生命 as 研究生|命 when 研究|生命 is cheaper).

    `lexicon`: word → cost (lower = preferred). Plain iterables get a
    default cost of `word_cost_base - word_cost_len * len(word)` so longer
    dictionary words win unless explicit costs say otherwise. Unknown
    characters cost `unk_cost` each, with a discount when they extend a
    same-character-class run (kuromoji's unknown-word grouping)."""

    def __init__(self, lexicon, *, unk_cost: float = 10.0,
                 unk_run_cost: float = 6.0,
                 word_cost_base: float = 8.0, word_cost_len: float = 3.0):
        if isinstance(lexicon, dict):
            self.costs = {w: float(c) for w, c in lexicon.items()}
        else:
            self.costs = {
                w: max(word_cost_base - word_cost_len * len(w), 1.0)
                for w in (lexicon or ())}
        self.max_len = max((len(w) for w in self.costs), default=1)
        self.unk_cost = unk_cost
        self.unk_run_cost = unk_run_cost

    def segment(self, text: str) -> List[str]:
        n = len(text)
        INF = float("inf")
        best = [INF] * (n + 1)
        back: List[Optional[int]] = [None] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] == INF:
                continue
            # dictionary edges
            for ln in range(1, min(self.max_len, n - i) + 1):
                w = text[i:i + ln]
                c = self.costs.get(w)
                if c is not None and best[i] + c < best[i + ln]:
                    best[i + ln] = best[i] + c
                    back[i + ln] = i
            # unknown single char; cheaper when continuing a same-class run
            # (so an OOV katakana loanword or digit string stays one token)
            cont = (i > 0 and back[i] == i - 1
                    and _char_class(text[i]) == _char_class(text[i - 1]))
            c = self.unk_run_cost if cont else self.unk_cost
            if best[i] + c < best[i + 1]:
                best[i + 1] = best[i] + c
                back[i + 1] = i
        # reconstruct, merging adjacent same-class unknown chars into runs
        cuts = []
        j = n
        while j > 0:
            cuts.append(j)
            j = back[j]
        cuts.append(0)
        cuts.reverse()
        pieces = [text[a:b] for a, b in zip(cuts, cuts[1:])]
        if self.unk_run_cost >= self.unk_cost:
            return pieces   # run-grouping disabled: unknowns stay unigram
        out: List[str] = []
        for p in pieces:
            if (out and len(p) == 1
                    and out[-1] not in self.costs and p not in self.costs
                    and _char_class(p) == _char_class(out[-1][-1])):
                out[-1] += p
            else:
                out.append(p)
        return out


# Small embedded starter lexicons (high-frequency words/particles) so the
# factories are useful out of the box; user dictionaries extend/override.
# The reference ships full analyzer dictionaries (~MBs); these cover the
# closed-class core the segmentation quality hinges on.
ZH_COMMON = (
    "的 了 是 在 不 我 有 他 这 中 大 来 上 国 个 到 说 们 为 子 和 你 地 出 道 "
    "也 时 年 得 就 那 要 下 以 生 会 自 着 去 之 过 家 学 对 可 她 里 后 小 么 "
    "我们 你们 他们 她们 这个 那个 什么 没有 知道 现在 时候 自己 大家 因为 "
    "所以 但是 可以 已经 还是 如果 虽然 时间 问题 工作 学习 学生 老师 朋友 "
    "中国 北京 研究 生命 科学 技术 经济 发展 社会 世界 国家 政府 人民 "
    "今天 明天 昨天 东西 地方 事情 开始 结束 喜欢 觉得 认为 希望 需要"
).split()

JA_COMMON = (
    "の は が を に で と も か ら な だ です ます した する いる ある なる "
    "これ それ あれ この その あの ここ そこ どこ わたし あなた かれ かのじょ "
    "こと もの とき ひと 私 僕 彼 彼女 日本 東京 学生 先生 学校 会社 仕事 "
    "時間 今日 明日 昨日 今 年 月 日 人 何 言葉 勉強 研究 世界 国 家族 友達 "
    "ありがとう こんにちは さようなら ください から まで より など について"
).split()


def _build_lexicon(base_words, user) -> dict:
    """base + user lexicon merge with one shared cost formula (user words
    cost slightly less, so they beat the embedded core at equal length)."""
    def cost(w, base, floor):
        return max(base - 3.0 * len(w), floor)

    lex = {w: cost(w, 8.0, 1.0) for w in base_words}
    if isinstance(user, dict):
        lex.update({w: float(c) for w, c in user.items()})
    else:
        lex.update({w: cost(w, 7.0, 0.5) for w in (user or ())})
    return lex


def _spans(text: str, classes) -> List:
    """Partition into (is_target, span) with CONSECUTIVE target-class runs
    coalesced — Japanese words cross script boundaries (kanji+okurigana
    like 食べる), so the lattice must see the whole CJK span."""
    out: List = []
    for run in _runs(text):
        tgt = _char_class(run[0]) in classes
        if out and out[-1][0] and tgt:
            out[-1] = (True, out[-1][1] + run)
        else:
            out.append((tgt, run))
    return out


class JapaneseTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-japanese` (kuromoji fork) — same
    algorithm class: min-cost lattice segmentation over a lexicon
    (LatticeSegmenter) seeded with the embedded JA_COMMON core; a user
    dictionary (iterable of words or word→cost dict) extends it."""

    _CJK = ("kanji", "hiragana", "katakana")

    def __init__(self, user_dictionary: Optional[Iterable[str]] = None, *,
                 base_lexicon: Optional[Iterable[str]] = None):
        super().__init__()
        base = JA_COMMON if base_lexicon is None else base_lexicon
        self._seg = LatticeSegmenter(_build_lexicon(base, user_dictionary))

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for is_cjk, span in _spans(unicodedata.normalize("NFKC", text),
                                   self._CJK):
            if is_cjk:
                toks.extend(self._seg.segment(span))
            else:
                toks.append(span)
        return _ListTokenizer(toks, self._pre)


class ChineseTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-chinese` (ansj analyzer) — min-cost
    lattice segmentation (ZH_COMMON core + user dictionary); degrades to
    unigram characters on fully-OOV spans like the reference."""

    def __init__(self, dictionary: Optional[Iterable[str]] = None, *,
                 base_lexicon: Optional[Iterable[str]] = None):
        super().__init__()
        base = ZH_COMMON if base_lexicon is None else base_lexicon
        # Chinese unknowns should NOT merge into runs (OOV hanzi stay
        # unigrams — the ansj fallback); a run discount would glue them.
        self._seg = LatticeSegmenter(_build_lexicon(base, dictionary),
                                     unk_run_cost=10.0)

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for is_hanzi, span in _spans(unicodedata.normalize("NFKC", text),
                                     ("kanji",)):
            if is_hanzi:
                toks.extend(self._seg.segment(span))
            else:
                toks.append(span)
        return _ListTokenizer(toks, self._pre)


_JOSA = ("은", "는", "이", "가", "을", "를", "의", "에", "에서", "으로",
         "로", "와", "과", "도", "만", "까지", "부터", "에게")


class KoreanTokenizerFactory(TokenizerFactory):
    """Reference: `deeplearning4j-nlp-korean` (twitter-text port)."""

    def __init__(self, strip_particles: bool = True):
        super().__init__()
        self.strip_particles = strip_particles

    def create(self, text: str) -> Tokenizer:
        toks: List[str] = []
        for word in text.split():
            w = word.strip(".,!?…·()[]\"'")
            if not w:
                continue
            if self.strip_particles and _char_class(w[-1]) == "hangul":
                for josa in sorted(_JOSA, key=len, reverse=True):
                    if len(w) > len(josa) and w.endswith(josa):
                        w = w[:-len(josa)]
                        break
            toks.append(w)
        return _ListTokenizer(toks, self._pre)


class _ListTokenizer(Tokenizer):
    """Tokenizer over a precomputed token list (factories above)."""

    def __init__(self, toks: List[str], pre):
        self._toks = toks
        self._pre = pre

    def tokens(self) -> List[str]:
        out = [self._pre.pre_process(t) if self._pre else t
               for t in self._toks]
        return [t for t in out if t]


# --------------------------------------------------------------------------
# Japanese morphological analysis (POS + readings + base forms)
# --------------------------------------------------------------------------
import dataclasses as _dc


@_dc.dataclass(frozen=True)
class Morpheme:
    """One analyzed token — kuromoji Token analogue (reference:
    deeplearning4j-nlp-japanese bundles a kuromoji fork whose Token
    carries surface / part-of-speech / reading / base form)."""

    surface: str
    pos: str                      # kuromoji-style main category (動詞 etc.)
    reading: Optional[str] = None   # katakana
    base: Optional[str] = None      # dictionary (base) form


def _hira_to_kata(s: str) -> str:
    return "".join(chr(ord(c) + 0x60) if 0x3041 <= ord(c) <= 0x3096 else c
                   for c in s)


# surface -> (POS, katakana reading). Closed-class core + common verbs
# (verbs carry a conjugation class for inflection generation below:
# "1" ichidan, "5" godan, "irr" irregular).
JA_MORPH: dict = {}
for _w, _r in (("の", "ノ"), ("は", "ハ"), ("が", "ガ"), ("を", "ヲ"),
               ("に", "ニ"), ("で", "デ"), ("と", "ト"), ("も", "モ"),
               ("か", "カ"), ("から", "カラ"), ("まで", "マデ"),
               ("より", "ヨリ"), ("など", "ナド"), ("について", "ニツイテ")):
    JA_MORPH[_w] = ("助詞", _r)
for _w, _r in (("です", "デス"), ("ます", "マス"), ("ました", "マシタ"),
               ("ません", "マセン"), ("た", "タ"), ("だ", "ダ"),
               ("ない", "ナイ"), ("な", "ナ"), ("ら", "ラ")):
    JA_MORPH[_w] = ("助動詞", _r)
for _w, _r in (("これ", "コレ"), ("それ", "ソレ"), ("あれ", "アレ"),
               ("ここ", "ココ"), ("そこ", "ソコ"), ("どこ", "ドコ"),
               ("わたし", "ワタシ"), ("あなた", "アナタ"), ("私", "ワタシ"),
               ("僕", "ボク"), ("彼", "カレ"), ("彼女", "カノジョ"),
               ("かれ", "カレ"), ("かのじょ", "カノジョ")):
    JA_MORPH[_w] = ("代名詞", _r)
for _w, _r in (("日本", "ニホン"), ("東京", "トウキョウ"),
               ("学生", "ガクセイ"), ("先生", "センセイ"),
               ("学校", "ガッコウ"), ("会社", "カイシャ"),
               ("仕事", "シゴト"), ("時間", "ジカン"), ("今日", "キョウ"),
               ("明日", "アシタ"), ("昨日", "キノウ"), ("今", "イマ"),
               ("年", "トシ"), ("月", "ツキ"), ("日", "ヒ"), ("人", "ヒト"),
               ("何", "ナニ"), ("言葉", "コトバ"), ("勉強", "ベンキョウ"),
               ("研究", "ケンキュウ"), ("世界", "セカイ"), ("国", "クニ"),
               ("家族", "カゾク"), ("友達", "トモダチ"), ("こと", "コト"),
               ("もの", "モノ"), ("とき", "トキ"), ("ひと", "ヒト")):
    JA_MORPH[_w] = ("名詞", _r)
for _w, _r in (("ありがとう", "アリガトウ"), ("こんにちは", "コンニチハ"),
               ("さようなら", "サヨウナラ")):
    JA_MORPH[_w] = ("感動詞", _r)
JA_MORPH["ください"] = ("動詞", "クダサイ")

# verb dictionary: base form -> (reading, conjugation class)
JA_VERBS = {
    "する": ("スル", "irr"), "いる": ("イル", "1"), "ある": ("アル", "5"),
    "なる": ("ナル", "5"), "食べる": ("タベル", "1"), "見る": ("ミル", "1"),
    "行く": ("イク", "5"), "来る": ("クル", "irr"), "思う": ("オモウ", "5"),
    "言う": ("イウ", "5"), "分かる": ("ワカル", "5"), "書く": ("カク", "5"),
    "読む": ("ヨム", "5"), "話す": ("ハナス", "5"), "使う": ("ツカウ", "5"),
    "作る": ("ツクル", "5"), "持つ": ("モツ", "5"), "出る": ("デル", "1"),
    "入る": ("ハイル", "5"), "待つ": ("マツ", "5"), "買う": ("カウ", "5"),
    "飲む": ("ノム", "5"), "泳ぐ": ("オヨグ", "5"), "死ぬ": ("シヌ", "5"),
    "遊ぶ": ("アソブ", "5"), "休む": ("ヤスム", "5"),
}

# godan final-kana -> (masu-stem kana, ta/te euphonic past, negative stem)
_GODAN = {
    "う": ("い", "った", "わ"), "つ": ("ち", "った", "た"),
    "る": ("り", "った", "ら"), "む": ("み", "んだ", "ま"),
    "ぶ": ("び", "んだ", "ば"), "ぬ": ("に", "んだ", "な"),
    "く": ("き", "いた", "か"), "ぐ": ("ぎ", "いだ", "が"),
    "す": ("し", "した", "さ"),
}


def _inflections(base: str, reading: str, klass: str):
    """Generate common inflected (surface, reading) pairs for one verb.

    Regular verbs substitute only the FINAL kana, so readings follow the
    same substitution on the base reading. Irregular verbs (する/来る)
    carry explicit stem readings — 来る's stem kanji reads ク only in the
    dictionary form (来た=キタ, 来ない=コナイ), which no suffix rule can
    derive."""
    rstem = reading[:-1]              # reading minus the final ル/ウ row kana
    if klass == "irr":
        stems = {"する": (("し", "シ"), ("した", "シタ"), ("し", "シ")),
                 "来る": (("来", "キ"), ("来た", "キタ"), ("来", "コ"))}
        (stem, stem_r), (past, past_r), (neg, neg_r) = stems[base]
    elif klass == "1":
        stem, stem_r = base[:-1], rstem
        past, past_r = stem + "た", rstem + "タ"
        neg, neg_r = stem, rstem
    else:
        k = base[-1]
        ms, pa, ns = _GODAN[k]
        stem, stem_r = base[:-1] + ms, rstem + _hira_to_kata(ms)
        past, past_r = base[:-1] + pa, rstem + _hira_to_kata(pa)
        neg, neg_r = base[:-1] + ns, rstem + _hira_to_kata(ns)
        if base == "行く":        # the one godan euphonic exception
            past, past_r = "行った", "イッタ"
    yield base, reading
    yield past, past_r                            # plain past
    te = "で" if past.endswith("だ") else "て"
    yield past[:-1] + te, past_r[:-1] + _hira_to_kata(te)   # te-form
    for suf in ("ます", "ました", "ません", "ましょう"):
        yield stem + suf, stem_r + _hira_to_kata(suf)       # polite row
    yield neg + "ない", neg_r + "ナイ"            # plain negative


# inflected surface -> (base form, reading) — built once
JA_INFLECTED = {}
for _b, (_r, _k) in JA_VERBS.items():
    for _surf, _read in _inflections(_b, _r, _k):
        JA_INFLECTED.setdefault(_surf, (_b, _read))


class JapaneseMorphologicalAnalyzer:
    """kuromoji-capability analogue: segment + POS-tag + readings + base
    forms. Segmentation is the same min-cost lattice as
    JapaneseTokenizerFactory, with the verb dictionary's generated
    inflected surfaces added so conjugated verbs stay one token
    (kuromoji's dictionary stores inflected entries the same way)."""

    def __init__(self, user_dictionary=None):
        words = dict(_build_lexicon(JA_COMMON, user_dictionary))
        for surf in JA_INFLECTED:
            words.setdefault(surf, max(8.0 - 3.0 * len(surf), 0.4))
        self._seg = LatticeSegmenter(words)

    def analyze(self, text: str) -> List[Morpheme]:
        # same NFKC normalization as JapaneseTokenizerFactory.create, so
        # half-width katakana / full-width latin take the same path
        text = unicodedata.normalize("NFKC", text)
        out: List[Morpheme] = []
        for is_cjk, span in _spans(text, ("kanji", "hiragana", "katakana")):
            if not is_cjk:
                for tok in span.split():
                    if tok:
                        out.append(Morpheme(
                            tok, "名詞" if tok[0].isalnum() else "記号"))
                continue
            for tok in self._seg.segment(span):
                out.append(self._morpheme(tok))
        return out

    def _morpheme(self, tok: str) -> Morpheme:
        if tok in JA_INFLECTED:
            base, reading = JA_INFLECTED[tok]
            return Morpheme(tok, "動詞", reading, base)
        if tok in JA_MORPH:
            pos, reading = JA_MORPH[tok]
            return Morpheme(tok, pos, reading, tok)
        cls = _char_class(tok[0])
        if cls == "katakana":
            return Morpheme(tok, "名詞", tok, tok)
        if cls == "hiragana":
            return Morpheme(tok, "助詞", _hira_to_kata(tok), tok)
        return Morpheme(tok, "名詞", None, tok)   # unknown kanji
