"""Word2Vec — large-batch jitted skipgram/CBOW with negative sampling or
hierarchical softmax.

Reference parity: `models/word2vec/Word2Vec.java` over
`models/sequencevectors/SequenceVectors.java` with learning algorithms
`models/embeddings/learning/impl/elements/{SkipGram,CBOW}.java` and storage
`models/embeddings/inmemory/InMemoryLookupTable.java` (syn0/syn1/syn1neg).

TPU redesign (SURVEY §7 hard part (c)): the reference's N hogwild threads
each exec batched native `AggregateSkipGram` ops against shared memory; here
pair generation happens on host (vectorized numpy) and ALL updates for a
batch of ~10⁴ pairs happen in one jitted step — gathers, sampled-softmax
loss, autodiff scatter-add grads, SGD with the classic linear LR decay.
Exact SGD semantics per batch; hogwild's lock-free races are gone.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, SentenceIterator, TokenizerFactory,
    tokenize_corpus,
)
from deeplearning4j_tpu.nlp.vocab import (
    HuffmanTree, VocabCache, build_vocab, unigram_table,
)


def _as_token_lists(corpus, tokenizer_factory) -> List[List[str]]:
    if isinstance(corpus, SentenceIterator):
        return tokenize_corpus(corpus, tokenizer_factory)
    if corpus and isinstance(corpus[0], str):
        return tokenize_corpus(corpus, tokenizer_factory)
    return [list(s) for s in corpus]


class Word2Vec:
    """Reference: `Word2Vec.Builder` surface mapped to kwargs."""

    def __init__(self, *, layer_size: int = 100, window: int = 5,
                 min_count: int = 5, negative: int = 5,
                 hierarchic_softmax: bool = False,
                 subsampling: float = 1e-3, epochs: int = 1,
                 learning_rate: float = 0.025, min_learning_rate: float = 1e-4,
                 batch_size: int = 8192, seed: int = 42,
                 use_cbow: bool = False,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.layer_size = layer_size
        self.window = window
        self.min_count = min_count
        self.negative = negative
        self.hs = hierarchic_softmax
        self.subsampling = subsampling
        self.epochs = epochs
        self.lr = learning_rate
        self.min_lr = min_learning_rate
        self.batch_size = batch_size
        self.seed = seed
        self.cbow = use_cbow
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None
        self._syn1: Optional[np.ndarray] = None

    # ------------------------------------------------------------ fitting
    def _index_sentences(self, sentences):
        idx = [
            np.array([self.vocab.index_of(w) for w in s], dtype=np.int64)
            for s in sentences
        ]
        return [s[s >= 0] for s in idx if (s >= 0).sum() > 1]

    def _setup(self, rng=None):
        """Allocate syn0/syn1 and build the jit step from self.vocab.
        Shared by local fit() and the distributed trainer."""
        V, D = len(self.vocab), self.layer_size
        if rng is None:
            rng = np.random.default_rng(self.seed)
        syn0 = ((rng.random((V, D), dtype=np.float32) - 0.5) / D)
        syn1 = np.zeros((V, D), dtype=np.float32)
        probs = unigram_table(self.vocab)
        counts = self.vocab.counts()
        total = counts.sum()
        if self.hs:
            HuffmanTree(self.vocab)
            codes, points, lens = HuffmanTree.padded_codes(self.vocab)
            step = self._make_hs_step(codes, points, lens)
            syn1 = np.zeros((max(V - 1, 1), D), dtype=np.float32)
        else:
            step = self._make_ns_step()
        # subsampling keep probability (word2vec formula)
        t = self.subsampling
        freq = counts / max(total, 1)
        keep = (np.sqrt(freq / t) + 1) * (t / np.maximum(freq, 1e-12)) \
            if t > 0 else np.ones(V)
        params = {"syn0": jnp.asarray(syn0), "syn1": jnp.asarray(syn1)}
        return {"params": params, "keep": np.clip(keep, 0, 1),
                "probs": probs, "step": step}

    def _run_epoch(self, params, idx_sentences, setup, rng, seen, total_est):
        """One pass over idx_sentences; returns (params, seen)."""
        keep, probs, step = setup["keep"], setup["probs"], setup["step"]
        centers, contexts = self._generate_pairs(idx_sentences, keep, rng)
        order = rng.permutation(len(centers))
        centers, contexts = centers[order], contexts[order]
        for lo in range(0, len(centers), self.batch_size):
            c = centers[lo:lo + self.batch_size]
            x = contexts[lo:lo + self.batch_size]
            if len(c) < 16:
                continue
            frac = min(seen / max(total_est, 1), 1.0)
            lr = max(self.lr * (1.0 - frac), self.min_lr)
            if self.hs:
                params = step(params, jnp.asarray(c), jnp.asarray(x),
                              jnp.asarray(lr, jnp.float32))
            else:
                negs = rng.choice(len(probs),
                                  size=(len(c), self.negative), p=probs)
                params = step(params, jnp.asarray(c), jnp.asarray(x),
                              jnp.asarray(negs), jnp.asarray(lr, jnp.float32))
            seen += len(c)
        return params, seen

    def fit(self, corpus) -> "Word2Vec":
        """Reference: `SequenceVectors.fit():187` (vocab build → Huffman →
        training threads → here: batched jit steps)."""
        sentences = _as_token_lists(corpus, self.tokenizer_factory)
        self.vocab = build_vocab(sentences, min_count=self.min_count)
        if len(self.vocab) == 0:
            raise ValueError("Empty vocabulary (min_count too high?)")
        rng = np.random.default_rng(self.seed)
        idx_sentences = self._index_sentences(sentences)
        setup = self._setup(rng)
        params = setup["params"]
        total_est = sum(len(s) for s in idx_sentences) * self.window \
            * max(self.epochs, 1)
        seen = 0
        for epoch in range(self.epochs):
            params, seen = self._run_epoch(
                params, idx_sentences, setup, rng, seen, total_est)
        self.syn0 = np.asarray(params["syn0"])
        self._syn1 = np.asarray(params["syn1"])
        return self

    def _generate_pairs(self, idx_sentences, keep, rng
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """Dynamic-window (center, context) pairs with frequency
        subsampling — vectorized host-side equivalent of the reference's
        per-thread sentence walk."""
        all_c, all_x = [], []
        for s in idx_sentences:
            if self.subsampling > 0:
                s = s[rng.random(len(s)) < keep[s]]
            n = len(s)
            if n < 2:
                continue
            b = rng.integers(1, self.window + 1, n)  # per-center dynamic window
            for off in range(1, self.window + 1):
                if n <= off:
                    break
                i = np.arange(n - off)
                m = b[i + off] >= off     # center i+off ← context i
                all_c.append(s[i + off][m])
                all_x.append(s[i][m])
                m = b[i] >= off           # center i ← context i+off
                all_c.append(s[i][m])
                all_x.append(s[i + off][m])
        if not all_c:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)
        return np.concatenate(all_c), np.concatenate(all_x)

    def _make_ns_step(self):
        cbow = self.cbow

        @jax.jit
        def step(params, centers, contexts, negatives, lr):
            def loss_fn(p):
                s0, s1 = p["syn0"], p["syn1"]
                if cbow:
                    h = s0[contexts]          # [B,D] (single-word context here)
                else:
                    h = s0[centers]
                tgt = contexts if not cbow else centers
                pos = jnp.einsum("bd,bd->b", h, s1[tgt])
                neg = jnp.einsum("bd,bkd->bk", h, s1[negatives])
                # SUM (not mean): per-pair update magnitude matches the
                # reference's per-example SGD semantics.
                return (jnp.sum(jax.nn.softplus(-pos))
                        + jnp.sum(jax.nn.softplus(neg)))

            grads = jax.grad(loss_fn)(params)
            return jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)

        return step

    def _make_hs_step(self, codes, points, lens):
        codes = jnp.asarray(codes)
        points = jnp.asarray(points)
        lens = jnp.asarray(lens)

        @jax.jit
        def step(params, centers, contexts, lr):
            def loss_fn(p):
                h = p["syn0"][centers]                     # [B,D]
                pt = points[contexts]                      # [B,L]
                cd = codes[contexts].astype(jnp.float32)   # [B,L]
                ln = lens[contexts]                        # [B]
                L = pt.shape[1]
                valid = jnp.arange(L)[None, :] < ln[:, None]
                logits = jnp.einsum("bd,bld->bl", h, p["syn1"][pt])
                # code bit 1 → sigmoid target 0 (word2vec convention):
                # loss = softplus(logit) if bit==1 else softplus(-logit)
                bce = jnp.where(valid, jax.nn.softplus(
                    jnp.where(cd > 0, logits, -logits)), 0.0)
                return jnp.sum(bce)

            grads = jax.grad(loss_fn)(params)
            return jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)

        return step

    # ------------------------------------------------------------ queries
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.syn0[i]

    def similarity(self, a: str, b: str) -> float:
        """Reference: `WordVectors.similarity`."""
        va, vb = self.word_vector(a), self.word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12
        return float(va @ vb / denom)

    def words_nearest(self, word_or_vec, n: int = 10) -> List[str]:
        """Reference: `WordVectors.wordsNearest`."""
        if isinstance(word_or_vec, str):
            v = self.word_vector(word_or_vec)
            exclude = {self.vocab.index_of(word_or_vec)}
            if v is None:
                return []
        else:
            v = np.asarray(word_or_vec, np.float32)
            exclude = set()
        norms = np.linalg.norm(self.syn0, axis=1) + 1e-12
        sims = self.syn0 @ v / (norms * (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            if i in exclude:
                continue
            out.append(self.vocab.word_at(int(i)))
            if len(out) >= n:
                break
        return out

    def accuracy(self, questions: Sequence[Tuple[str, str, str, str]]) -> float:
        """Analogy accuracy (a:b :: c:d). Reference: Word2Vec accuracy tests."""
        good = total = 0
        for a, b, c, d in questions:
            va, vb, vc = (self.word_vector(w) for w in (a, b, c))
            if va is None or vb is None or vc is None:
                continue
            pred = self.words_nearest(vb - va + vc, 4)
            total += 1
            if d in pred:
                good += 1
        return good / max(total, 1)
