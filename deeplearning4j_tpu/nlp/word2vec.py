"""Word2Vec — tokenized-text front-end over the SequenceVectors engine.

Reference parity: `models/word2vec/Word2Vec.java` over
`models/sequencevectors/SequenceVectors.java` with learning algorithms
`models/embeddings/learning/impl/elements/{SkipGram,CBOW}.java` and storage
`models/embeddings/inmemory/InMemoryLookupTable.java` (syn0/syn1/syn1neg).

The whole training engine (vocab → Huffman/negative tables → batched jitted
steps) lives in `nlp/sequence_vectors.py` — shared with ParagraphVectors
and DeepWalk exactly as the reference shares SequenceVectors. This class
adds only the text pipeline: sentence iterators + tokenizer factory.
"""

from __future__ import annotations

from typing import List, Optional

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, SentenceIterator, TokenizerFactory,
    tokenize_corpus,
)


def _as_token_lists(corpus, tokenizer_factory) -> List[List[str]]:
    if isinstance(corpus, SentenceIterator):
        return tokenize_corpus(corpus, tokenizer_factory)
    corpus = list(corpus)
    if corpus and isinstance(corpus[0], str):
        return tokenize_corpus(corpus, tokenizer_factory)
    return [list(s) for s in corpus]


class Word2Vec(SequenceVectors):
    """Reference: `Word2Vec.Builder` surface mapped to kwargs."""

    def __init__(self, *, use_cbow: bool = False,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 **kw):
        kw.setdefault("learning_algorithm", "cbow" if use_cbow
                      else "skipgram")
        super().__init__(**kw)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()

    def fit(self, corpus) -> "Word2Vec":
        """Reference: `SequenceVectors.fit():187` reached through the
        Word2Vec text pipeline (sentence iterator → tokenizer)."""
        sentences = _as_token_lists(corpus, self.tokenizer_factory)
        SequenceVectors.fit(self, sentences)
        return self
