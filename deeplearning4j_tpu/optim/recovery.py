"""RecoveryPlan — preemption-proof fit plumbing shared by every fit loop.

ISSUE 6 tentpole: production TPU pods get preempted, and before this
module only `ParallelWrapper.fit` could checkpoint or resume — the
closures lived inline in `data_parallel.py` and neither
`MultiLayerNetwork.fit` nor `ComputationGraph.fit` had any recovery
story. The plan threads the existing `ShardedCheckpointer` +
`PreemptionHandler` through `TrainingExecutor`'s seams
(`before_batch` / `after_dispatch` / `epoch_start` / `epoch_end`) so all
three fit entry points share ONE tested recovery path:

- **Continuous async checkpoints off the critical path**: saves happen
  at dispatch boundaries (`after_dispatch`), where params/updater/rng
  are a consistent snapshot even under fused `steps_per_dispatch>1`
  (the scan window is indivisible, so the cadence coarsens to window
  ends; a resume into a partial window replays via SKIP and the
  executor's drain path truncates the tail per-step — bit-identical rng
  chain either way). The writer runs on the checkpointer's daemon
  thread; nothing here reads the loss, so the executor's ≤1 host
  sync/epoch contract survives (asserted in tests/test_chaos_recovery).
- **Exact mid-epoch resume** from (step, rng-chain, iterator cursor):
  `resume="auto"` restores the newest committed checkpoint (via
  `restore_fn` when the caller owns shardings — ParallelWrapper), then
  replays the epoch's consumed batches as SKIPs.
- **Black-box continuity**: a resumed run records the prior crash's
  FlightRecorder dump as a breadcrumb (`resume` ring event), so the
  restart carries its predecessor's last seconds; a preemption stop
  records `preemption_checkpoint` with the exact cursor.
- **Clean preemption**: `preemption=True` installs a SIGTERM handler for
  the duration of the fit (degrading gracefully off the main thread —
  see `PreemptionHandler.install`); the flag, or a caller `stop_fn`,
  stops training at the next batch boundary and `finalize()` writes a
  final exact-position snapshot.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

from deeplearning4j_tpu.optim.executor import SKIP, STOP

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["RecoveryPlan", "AUTO", "build_plan", "run_with_recovery"]

AUTO = "auto"


def build_plan(net, **kw) -> Optional["RecoveryPlan"]:
    """A RecoveryPlan when any recovery kwarg is set, else None — so the
    plain `fit()` fast path stays hook-free (no per-batch indirection)."""
    # NB: `is not None`, not truthiness — resume={} (a restore with no
    # recorded position) must still build a plan
    if (kw.get("checkpointer") is None and kw.get("resume") is None
            and kw.get("stop_fn") is None and not kw.get("preemption")):
        return None
    return RecoveryPlan(net, **kw)


def run_with_recovery(execu, plan: Optional["RecoveryPlan"],
                      iterable, epochs: int):
    """Drive `execu.run` under a plan's lifecycle: install the handler,
    resume from the plan's epoch, flush the writer on BOTH exits (without
    masking a training crash), snapshot the exact stop position."""
    if plan is None:
        return execu.run(iterable, epochs)
    with plan:
        try:
            execu.run(iterable, epochs, start_epoch=plan.start_epoch)
        except BaseException:
            plan.abort()
            raise
    plan.finalize(execu.stopped)
    return execu.net


class RecoveryPlan:
    """One fit() call's recovery state machine over the executor seams.

    Parameters
    ----------
    net : the model (params_tree / updater_state / state_tree / _rng /
        iteration / epoch — the ShardedCheckpointer contract).
    checkpointer : Optional[ShardedCheckpointer]; saves every
        `checkpoint_every` iterations at dispatch boundaries, plus a
        final snapshot on early stop.
    resume : None | position dict (from `restore_into*`) | "auto"
        ("auto" restores the newest committed step itself — via
        `restore_fn` when given, else `checkpointer.restore_into(net)`).
    stop_fn : extra stop predicate checked at batch boundaries.
    preemption : None | PreemptionHandler | True. `True` builds a
        SIGTERM handler owned (installed/uninstalled) by the plan's
        context manager; an explicit handler is the caller's to install.
    prepare : per-batch transform applied after the skip/stop gate
        (ParallelWrapper's pad-to-divisible hook).
    """

    def __init__(self, net, *, checkpointer=None, checkpoint_every: int = 1,
                 resume=None, stop_fn: Optional[Callable[[], bool]] = None,
                 preemption=None, prepare: Optional[Callable] = None,
                 restore_fn: Optional[Callable[[], Dict]] = None):
        self.net = net
        self.checkpointer = checkpointer
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.stop_fn = stop_fn
        self.prepare = prepare
        self._owns_handler = preemption is True
        if preemption is True:
            from deeplearning4j_tpu.parallel.elastic import PreemptionHandler
            preemption = PreemptionHandler()
        self.handler = preemption or None
        if resume == AUTO:
            resume = self._auto_restore(restore_fn)
        self.resume = resume
        self.start_epoch = int(net.epoch) if resume is not None else 0
        self.skip = int((resume or {}).get("batch_in_epoch", 0))
        self.last_batch_index = self.skip - 1
        self._last_saved = int(net.iteration)
        self.stopped = False
        if resume is not None:
            self._resume_breadcrumb()

    # ------------------------------------------------------------ setup
    def _auto_restore(self, restore_fn):
        ck = self.checkpointer
        if ck is None and restore_fn is None:
            raise ValueError(
                'resume="auto" has nothing to restore from: pass '
                "checkpointer=... (or an explicit resume position dict)")
        if ck is None or ck.latest_step() is None:
            return None
        if restore_fn is not None:
            return restore_fn()
        return ck.restore_into(self.net)

    def _resume_breadcrumb(self):
        """The restart carries its predecessor's black box: point the
        ring at the prior crash dump (if one exists on disk)."""
        from deeplearning4j_tpu.observe.flight import get_flight, latest_dump
        prior = latest_dump()
        get_flight().record(
            "resume", iteration=int(self.net.iteration),
            epoch=int(self.net.epoch), batch_in_epoch=self.skip,
            prior_dump=prior)
        if prior:
            logger.info(
                "Resuming at iteration %d (epoch %d, batch %d); prior "
                "flight dump: %s", self.net.iteration, self.net.epoch,
                self.skip, prior)

    # --------------------------------------------------- executor seams
    def should_stop(self) -> bool:
        if self.handler is not None and self.handler.preempted:
            return True
        return bool(self.stop_fn is not None and self.stop_fn())

    def before_batch(self, bi: int, ds):
        if bi < self.skip:
            return SKIP          # resume replay: already trained
        if self.should_stop():
            return STOP
        if self.prepare is not None:
            ds = self.prepare(ds)
        return ds

    def after_dispatch(self, bi: int) -> None:
        self.last_batch_index = bi
        if self.checkpointer is None:
            return
        it = int(self.net.iteration)
        # modulo keeps the unfused cadence byte-compatible with the old
        # inline closure; the distance test catches cadences a K-step
        # scan window jumps clean over
        if (it % self.checkpoint_every == 0
                or it - self._last_saved >= self.checkpoint_every):
            self._save(bi + 1)

    def epoch_start(self) -> None:
        # a stop before this epoch's first non-skipped batch must
        # checkpoint the RESUMED position (skip batches are already
        # trained), not the previous epoch's tail
        self.last_batch_index = self.skip - 1

    def epoch_end(self) -> None:
        self.skip = 0

    # --------------------------------------------------------- lifecycle
    def __enter__(self) -> "RecoveryPlan":
        if self.handler is not None and self._owns_handler:
            self.handler.install()
        return self

    def __exit__(self, *exc) -> bool:
        if self.handler is not None and self._owns_handler:
            self.handler.uninstall()
        return False

    def _save(self, batch_in_epoch: int) -> None:
        self.checkpointer.save(
            self.net, step=int(self.net.iteration),
            position={"batch_in_epoch": int(batch_in_epoch)})
        self._last_saved = int(self.net.iteration)

    def finalize(self, stopped: bool) -> None:
        """After a clean `run()`: snapshot the exact stop position when
        training ended early, then flush the writer (re-raising any
        writer error — a silently failed checkpoint is a lost run)."""
        self.stopped = bool(stopped)
        ck = self.checkpointer
        if ck is None:
            return
        if stopped:
            if int(self.net.iteration) != self._last_saved:
                # the periodic cadence didn't cover the last dispatch
                self._save(self.last_batch_index + 1)
            from deeplearning4j_tpu.observe.flight import get_flight
            get_flight().record(
                "preemption_checkpoint", iteration=int(self.net.iteration),
                epoch=int(self.net.epoch),
                batch_in_epoch=self.last_batch_index + 1)
        ck.wait()

    def abort(self) -> None:
        """On the exception path: flush the writer WITHOUT raising — the
        original crash must propagate unmasked. Writer errors are
        recorded on the flight ring and logged instead."""
        ck = self.checkpointer
        if ck is None:
            return
        try:
            ck.wait()
        except Exception as e:
            logger.warning(
                "checkpoint writer failed while handling a training "
                "crash: %s: %s", type(e).__name__, e)
            try:
                from deeplearning4j_tpu.observe.flight import get_flight
                get_flight().record("checkpoint_writer_error",
                                    error=type(e).__name__,
                                    message=str(e)[:200])
            # graft: allow(GL403): breadcrumb only — the training crash
            # already propagating is the payload
            except Exception:
                pass
