"""Gradient updaters (optimizer rules) as pure pytree transforms.

Reference parity: ND4J's `GradientUpdater` implementations (Sgd, Adam, AdaMax,
Nadam, AMSGrad, Nesterovs, AdaGrad, AdaDelta, RmsProp, NoOp) applied through
DL4J's `UpdaterBlock.update()` (`nn/updater/UpdaterBlock.java:101-160`): the
reference transforms the gradient IN PLACE into the update over one contiguous
state view; here the same math is a pure function over pytrees — XLA fuses the
whole update into the train step, and optimizer state shards with the params
(ZeRO-style) under `jax.sharding` instead of living in one host-side view.

API: ``state = u.init(params)``; ``updates, state = u.apply(grads, state,
params, step)``; caller does ``params = params - updates`` (the reference's
`StepFunction.step` — `optimize/solvers/StochasticGradientDescent.java:79`).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.optim.schedules import as_schedule
from deeplearning4j_tpu.utils.serde import register_serde

_tmap = jax.tree_util.tree_map


def _lr(self, step):
    return as_schedule(self.learning_rate).value(step)


class Updater:
    """Base updater. Subclasses are frozen dataclasses (JSON-serializable).

    `sharded_state` names the state keys that are param-shaped moments —
    the leaves the sharding spine (`parallel.mesh.MeshContext`) may
    partition across the replica axis (cross-replica weight-update
    sharding, arXiv:2004.13336). Scalar or irregular state must stay off
    this list; stateless updaters leave it empty.
    """

    sharded_state = ()   # state keys holding param-shaped moments

    def init(self, params) -> Any:
        return ()

    def apply(self, grads, state, params, step):
        raise NotImplementedError

    def update_with_params(self, grads, state, params, step):
        """The whole optimizer step as ONE seam: returns (new_params,
        new_state). The default composes `apply` with the subtraction the
        step functions used to do inline, preserving dtypes identically
        (schedules may promote to f32; params/state keep their configured
        dtype for bf16 training and buffer donation). Adam/Nesterovs
        override this to route through the one-pass fused Pallas kernel
        (`ops/fused_update.py`) when `kernel_defaults.fused_update_policy`
        says it wins — the seam exists so that choice is per-updater,
        not per-model."""
        upd, st = self.apply(grads, state, params, step)
        new_params = _tmap(lambda a, b: a - b.astype(a.dtype), params, upd)
        new_state = _tmap(lambda n, o: n.astype(o.dtype), st, state)
        return new_params, new_state

    # learning-rate accessor shared by all (schedule-aware)
    lr = _lr


def _fused_interpret() -> bool:
    """An env-forced fused update off-TPU runs the kernel in interpret
    mode (the CPU parity/integration seam; slow but exact)."""
    return jax.default_backend() != "tpu"


def _moments_replica_sharded() -> bool:
    """Trace-time check: is an active sharding spine partitioning the
    optimizer moments across the replica axis? The fused-update Pallas
    kernels are slot-local (one contiguous buffer per leaf) — running
    them over replica-sharded moments would force XLA to all-gather the
    very state the spine just split, so the fused path defers to the
    XLA update whenever the spine owns moment placement."""
    from deeplearning4j_tpu.parallel.mesh import current_mesh_context

    ctx = current_mesh_context()
    return (ctx is not None and ctx.shard_opt_state
            and ctx.data_size > 1)


@register_serde
@dataclasses.dataclass(frozen=True)
class NoOp(Updater):
    """Reference: NoOp updater (frozen layers use this)."""

    def apply(self, grads, state, params, step):
        return _tmap(jnp.zeros_like, grads), state


@register_serde
@dataclasses.dataclass(frozen=True)
class Sgd(Updater):
    """Reference: org.nd4j.linalg.learning.Sgd — update = lr * g."""
    learning_rate: Any = 1e-3

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        return _tmap(lambda g: lr * g, grads), state


@register_serde
@dataclasses.dataclass(frozen=True)
class Nesterovs(Updater):
    """Reference: Nesterovs momentum (DL4J default momentum 0.9).

    Matches ND4J NesterovsUpdater: v' = mu*v - lr*g; update = -(mu*v' - lr*g)
    i.e. params += mu*v' - lr*g.
    """
    learning_rate: Any = 0.1
    momentum: float = 0.9
    sharded_state = ("v",)

    def init(self, params):
        return {"v": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        mu = self.momentum
        v_new = _tmap(lambda v, g: mu * v - lr * g, state["v"], grads)
        updates = _tmap(lambda vn, g: -(mu * vn - lr * g), v_new, grads)
        return updates, {"v": v_new}

    def update_with_params(self, grads, state, params, step):
        from deeplearning4j_tpu.ops.kernel_defaults import (
            fused_update_policy,
        )

        if fused_update_policy("nesterov") != "fused" \
                or _moments_replica_sharded():
            return super().update_with_params(grads, state, params, step)
        from deeplearning4j_tpu.ops.fused_update import nesterov_update

        lr = jnp.asarray(self.lr(step), jnp.float32)
        interp = _fused_interpret()
        lp, treedef = jax.tree_util.tree_flatten(params)
        lg = treedef.flatten_up_to(grads)
        lv = treedef.flatten_up_to(state["v"])
        outs = [nesterov_update(p, g, v, lr, momentum=self.momentum,
                                interpret=interp)
                for p, g, v in zip(lp, lg, lv)]
        return (treedef.unflatten([o[0] for o in outs]),
                {"v": treedef.unflatten([o[1] for o in outs])})


@register_serde
@dataclasses.dataclass(frozen=True)
class Adam(Updater):
    """Reference: AdamUpdater (bias-corrected first/second moments)."""
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    sharded_state = ("m", "v")

    def init(self, params):
        z = _tmap(jnp.zeros_like, params)
        return {"m": z, "v": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        b1, b2 = self.beta1, self.beta2
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        bc = jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
        updates = _tmap(lambda m, v: lr * bc * m / (jnp.sqrt(v) + self.epsilon), m, v)
        return updates, {"m": m, "v": v}

    def update_with_params(self, grads, state, params, step):
        from deeplearning4j_tpu.ops.kernel_defaults import (
            fused_update_policy,
        )

        if fused_update_policy("adam") != "fused" \
                or _moments_replica_sharded():
            return super().update_with_params(grads, state, params, step)
        from deeplearning4j_tpu.ops.fused_update import adam_update

        # Per-step scalars (schedule + bias correction) fold into ONE
        # traced coefficient; the kernel does the per-element work.
        t = jnp.asarray(step, jnp.float32) + 1.0
        b1, b2 = self.beta1, self.beta2
        lrbc = self.lr(step) * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
        interp = _fused_interpret()
        lp, treedef = jax.tree_util.tree_flatten(params)
        lg = treedef.flatten_up_to(grads)
        lm = treedef.flatten_up_to(state["m"])
        lv = treedef.flatten_up_to(state["v"])
        outs = [adam_update(p, g, m, v, lrbc, beta1=b1, beta2=b2,
                            eps=self.epsilon, interpret=interp)
                for p, g, m, v in zip(lp, lg, lm, lv)]
        return (treedef.unflatten([o[0] for o in outs]),
                {"m": treedef.unflatten([o[1] for o in outs]),
                 "v": treedef.unflatten([o[2] for o in outs])})


@register_serde
@dataclasses.dataclass(frozen=True)
class AdaMax(Updater):
    """Reference: AdaMaxUpdater — infinity-norm Adam variant."""
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    sharded_state = ("m", "u")

    def init(self, params):
        return {"m": _tmap(jnp.zeros_like, params), "u": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        b1 = self.beta1
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        u = _tmap(lambda u, g: jnp.maximum(self.beta2 * u, jnp.abs(g)), state["u"], grads)
        scale = lr / (1.0 - b1**t)
        updates = _tmap(lambda m, u: scale * m / (u + self.epsilon), m, u)
        return updates, {"m": m, "u": u}


@register_serde
@dataclasses.dataclass(frozen=True)
class Nadam(Updater):
    """Reference: NadamUpdater — Nesterov-accelerated Adam."""
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    sharded_state = ("m", "v")

    def init(self, params):
        return {"m": _tmap(jnp.zeros_like, params), "v": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        b1, b2 = self.beta1, self.beta2
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        mc = 1.0 - b1**t
        vc = 1.0 - b2**t
        updates = _tmap(
            lambda m, v, g: lr
            * (b1 * m / mc + (1 - b1) * g / mc)
            / (jnp.sqrt(v / vc) + self.epsilon),
            m, v, grads,
        )
        return updates, {"m": m, "v": v}


@register_serde
@dataclasses.dataclass(frozen=True)
class AMSGrad(Updater):
    """Reference: AMSGradUpdater — Adam with non-decreasing v-hat."""
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    sharded_state = ("m", "v", "vhat")

    def init(self, params):
        z = _tmap(jnp.zeros_like, params)
        return {"m": z, "v": _tmap(jnp.zeros_like, params), "vhat": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        b1, b2 = self.beta1, self.beta2
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        vhat = _tmap(jnp.maximum, state["vhat"], v)
        updates = _tmap(lambda m, vh: lr * m / (jnp.sqrt(vh) + self.epsilon), m, vhat)
        return updates, {"m": m, "v": v, "vhat": vhat}


@register_serde
@dataclasses.dataclass(frozen=True)
class AdaGrad(Updater):
    """Reference: AdaGradUpdater."""
    learning_rate: Any = 1e-1
    epsilon: float = 1e-6
    sharded_state = ("h",)

    def init(self, params):
        return {"h": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        h = _tmap(lambda h, g: h + g * g, state["h"], grads)
        updates = _tmap(lambda g, h: lr * g / (jnp.sqrt(h) + self.epsilon), grads, h)
        return updates, {"h": h}


@register_serde
@dataclasses.dataclass(frozen=True)
class AdaDelta(Updater):
    """Reference: AdaDeltaUpdater (rho/epsilon; no explicit LR)."""
    rho: float = 0.95
    epsilon: float = 1e-6
    sharded_state = ("Eg", "Ex")

    def init(self, params):
        return {"Eg": _tmap(jnp.zeros_like, params), "Ex": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        rho, eps = self.rho, self.epsilon
        Eg = _tmap(lambda a, g: rho * a + (1 - rho) * g * g, state["Eg"], grads)
        updates = _tmap(
            lambda g, eg, ex: g * jnp.sqrt(ex + eps) / jnp.sqrt(eg + eps),
            grads, Eg, state["Ex"],
        )
        Ex = _tmap(lambda a, u: rho * a + (1 - rho) * u * u, state["Ex"], updates)
        return updates, {"Eg": Eg, "Ex": Ex}


@register_serde
@dataclasses.dataclass(frozen=True)
class RmsProp(Updater):
    """Reference: RmsPropUpdater."""
    learning_rate: Any = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8
    sharded_state = ("g2",)

    def init(self, params):
        return {"g2": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        d = self.rms_decay
        g2 = _tmap(lambda a, g: d * a + (1 - d) * g * g, state["g2"], grads)
        updates = _tmap(lambda g, a: lr * g / (jnp.sqrt(a) + self.epsilon), grads, g2)
        return updates, {"g2": g2}


#: Every param-shaped moment key any built-in updater declares — the
#: sharding spine's default answer to "which updater-state leaves may be
#: partitioned across the replica axis" when it cannot see the per-layer
#: updater instances (e.g. re-sharding a checkpoint tree).
MOMENT_STATE_KEYS = frozenset(
    k for cls in (Nesterovs, Adam, AdaMax, Nadam, AMSGrad, AdaGrad,
                  AdaDelta, RmsProp)
    for k in cls.sharded_state)


def resolve_updater(u) -> Updater:
    """Accept an Updater instance or a name string ('adam', 'sgd', ...)."""
    if isinstance(u, Updater):
        return u
    names = {
        "sgd": Sgd, "adam": Adam, "adamax": AdaMax, "nadam": Nadam,
        "amsgrad": AMSGrad, "nesterovs": Nesterovs, "adagrad": AdaGrad,
        "adadelta": AdaDelta, "rmsprop": RmsProp, "noop": NoOp, "none": NoOp,
    }
    key = str(u).lower()
    if key not in names:
        raise ValueError(f"Unknown updater {u!r}; known: {sorted(names)}")
    return names[key]()
