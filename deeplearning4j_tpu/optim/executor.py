"""Async-dispatch training executor: the shared fit-loop engine.

Reference parity: the reference's fit loops (`MultiLayerNetwork.fit:1046`,
`ComputationGraph.fit:778`, `ParallelWrapper.fit:409`) each re-implement the
same epoch/listener/score plumbing AND block the dispatch pipeline every
step reading the scalar score off-device. Here that plumbing lives in ONE
executor with TPU-native dispatch semantics (PyGraph, arXiv:2503.19779, is
the GPU analogue — keep the accelerator queue full, stop paying host
round-trips per step):

- **Deferred loss sync** (`LossTracker`): the step functions return the
  loss as a DEVICE array; the tracker only materializes a Python float on
  demand (``score_`` access, a listener calling ``float(score)``, an
  every-N ``sync_every`` cadence, or epoch end). The steady-state hot loop
  performs ZERO mandatory host syncs — JAX's async dispatch keeps N steps
  in flight while the host runs ahead enqueueing more.
- **Fused multi-step execution** (`steps_per_dispatch=K`): K same-shape
  batches are stacked and the donated train step runs under `lax.scan` in
  a single dispatch — the TPU analogue of CUDA-graph capture. The executor
  transparently falls back to per-step dispatch for batches that need
  per-step visibility (tBPTT chunking, non-SGD solvers, shape changes,
  resume/stop/checkpoint seams).
- **Listener contract**: ``iteration_done`` receives the *device* loss;
  listeners that read it (``float(score)``) pay the sync they ask for,
  listeners that don't are free. Epoch end always materializes once so
  ``score_`` is a float at every epoch boundary (≤1 sync/epoch).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional

from deeplearning4j_tpu.observe import get_registry, reqtrace, span
from deeplearning4j_tpu.observe.attribution import (
    StepAttribution, attribution_enabled,
)
from deeplearning4j_tpu.observe.commsmon import get_reshard_witness
from deeplearning4j_tpu.observe.devicemon import maybe_start_monitor
from deeplearning4j_tpu.observe.flight import get_flight
from deeplearning4j_tpu.observe.watchdog import get_watchdog

__all__ = ["LossTracker", "TrainingExecutor", "SKIP", "STOP"]

# before_batch sentinels: skip this batch (resume replay) / stop cleanly
SKIP = object()
STOP = object()


def _is_device_array(x) -> bool:
    import jax

    return isinstance(x, jax.Array)


class LossTracker:
    """Deferred-sync score holder.

    Stores the most recent loss as whatever the step returned (device
    array or float) and converts to a Python float lazily, caching the
    result. ``host_syncs`` counts actual device→host materializations —
    the instrumentation seam the perf guard asserts on.

    ``sync_every=N`` forces a materialization every N updates (the
    listener-cadence knob); 0 (default) defers until ``value`` is read or
    ``materialize()`` is called (the executor calls it once per epoch).
    """

    def __init__(self, sync_every: int = 0):
        self.sync_every = int(sync_every)
        self._raw: Any = None
        self._cached: Optional[float] = None
        self._since_sync = 0
        self.host_syncs = 0     # device materializations (perf-guard seam)
        self.updates = 0
        # attribution seam: fn(block_ms) invoked after a device loss
        # materializes, with how long the float() blocked — THE measured
        # device boundary StepAttribution infers device time from
        self.on_block: Optional[Callable[[float], None]] = None

    def set(self, loss) -> None:
        """Overwrite the tracked loss without counting an update (the
        ``score_`` setter seam — solvers/earlystopping assign floats)."""
        self._raw = loss
        self._cached = None

    def update(self, loss) -> None:
        self.set(loss)
        self.updates += 1
        self._since_sync += 1
        if self.sync_every and self._since_sync >= self.sync_every:
            self.materialize()

    @property
    def value(self) -> Optional[float]:
        """The tracked loss as a float — THIS is the sync point."""
        if self._raw is None:
            return None
        if self._cached is None:
            blocked = _is_device_array(self._raw)
            if blocked:
                self.host_syncs += 1
            t0 = time.perf_counter()
            self._cached = float(self._raw)
            if blocked and self.on_block is not None:
                try:
                    self.on_block((time.perf_counter() - t0) * 1e3)
                # graft: allow(GL403): attribution must never break the
                # fit loop; the loss value below is the payload
                except Exception:
                    pass
            self._since_sync = 0
        return self._cached

    def peek(self):
        """The loss without forcing a sync (device array if never read)."""
        return self._raw if self._cached is None else self._cached

    def materialize(self) -> Optional[float]:
        return self.value


def _arr_sig(a):
    return None if a is None else (tuple(a.shape), str(getattr(a, "dtype", "")))


def batch_signature(ds):
    """Structural signature of a DataSet/MultiDataSet — two batches fuse
    into one `lax.scan` dispatch only when their signatures match (same
    shapes, dtypes, and mask presence ⇒ same compiled program)."""
    if hasattr(ds, "features_masks"):   # MultiDataSet
        return ("m",
                tuple(_arr_sig(f) for f in ds.features),
                tuple(_arr_sig(l) for l in ds.labels),
                tuple(_arr_sig(x) for x in (ds.features_masks or ())),
                tuple(_arr_sig(x) for x in (ds.labels_masks or ())))
    return ("d", _arr_sig(ds.features), _arr_sig(ds.labels),
            _arr_sig(ds.features_mask), _arr_sig(ds.labels_mask))


class TrainingExecutor:
    """The shared epoch/batch/listener loop with async-dispatch semantics.

    The model (or parallel trainer) supplies the step callables; the
    executor owns iteration bookkeeping, the fused-dispatch buffer, ETL
    timing, listener fan-out, and the epoch-end materialization.

    Hooks:
      step(ds) -> loss                one training step (device loss)
      fused_step(batches) -> (K,)    K stacked steps in one dispatch
      can_fuse(ds) -> bool           batch eligible for fusion
      before_batch(bi, ds) -> ds | SKIP | STOP
      after_step(bi)                 post-iteration seam (per _finish)
      after_dispatch(bi)             post-DISPATCH seam: fires once per
                                     device dispatch (per step unfused,
                                     per K-step scan window fused), at a
                                     point where params/updater/rng are a
                                     consistent snapshot — the
                                     checkpointing seam (RecoveryPlan)
      epoch_start() / epoch_end()    per-epoch trainer state

    `mesh_ctx` (a `parallel.mesh.MeshContext`) scopes the sharding spine
    over the whole loop: step-fn tracing, batch placement (the prefetch
    iterator's default put), and trace-time kernel policies all see ONE
    mesh while the executor runs.
    """

    def __init__(self, net, *, step: Callable,
                 fused_step: Optional[Callable] = None,
                 can_fuse: Optional[Callable] = None,
                 steps_per_dispatch: int = 1,
                 before_batch: Optional[Callable] = None,
                 after_step: Optional[Callable] = None,
                 after_dispatch: Optional[Callable] = None,
                 epoch_start: Optional[Callable] = None,
                 epoch_end: Optional[Callable] = None,
                 mesh_ctx=None):
        self.net = net
        self.mesh_ctx = mesh_ctx
        self.step = step
        self.fused_step = fused_step
        self.can_fuse = can_fuse or (lambda ds: False)
        self.k = max(1, int(steps_per_dispatch or 1))
        self.before_batch = before_batch
        self.after_step = after_step
        self.after_dispatch = after_dispatch
        self.epoch_start = epoch_start
        self.epoch_end = epoch_end
        self.stopped = False
        self._attr: Optional[StepAttribution] = None
        # per-epoch request trace (reqtrace) — None when sampling is off,
        # so the hot loop pays one attribute read per dispatch window
        self._rt = None
        # commsmon reshard witness — None when DL4J_TPU_COMMSMON is off,
        # so the disabled hot loop pays one attribute read per dispatch
        self._reshard = get_reshard_witness()
        reg = get_registry()
        self._iter_counter = reg.counter("train_iterations")
        self._etl_hist = reg.histogram("train_etl_ms")
        self._dispatch_hist = reg.histogram("train_dispatch_ms")

    # ------------------------------------------------------------- loop
    def run(self, iterable, epochs: int, *, start_epoch: int = 0):
        if self.mesh_ctx is not None:
            # lazy import: parallel.mesh pulls no optim modules, but the
            # parallel package __init__ imports this one
            from deeplearning4j_tpu.parallel.mesh import use_mesh_context
            with use_mesh_context(self.mesh_ctx):
                return self._run(iterable, epochs, start_epoch=start_epoch)
        return self._run(iterable, epochs, start_epoch=start_epoch)

    def _run(self, iterable, epochs: int, *, start_epoch: int = 0):
        net = self.net
        listeners = net.listeners
        # registry handles cached once per run; _finish only bumps them.
        # Spans carry only host-side scalars — never the device loss.
        reg = get_registry()
        self._iter_counter = reg.counter("train_iterations")
        self._etl_hist = reg.histogram("train_etl_ms")
        self._dispatch_hist = reg.histogram("train_dispatch_ms")
        # black box + device telemetry: wire the span ring before the
        # first fit span so a crash dump carries this run from the start
        flight = get_flight()
        maybe_start_monitor()
        tracker = getattr(net, "_loss_tracker", None)
        attr = None
        if attribution_enabled() and tracker is not None:
            attr = StepAttribution(reg)
            # PerformanceListener reads the measured device step time
            # (MFU denominator) from here
            net._attribution = attr
            tracker.on_block = attr.on_device_block
        self._attr = attr
        try:
            with span("fit", epochs=epochs, start_epoch=start_epoch,
                      steps_per_dispatch=self.k):
                for l in listeners:
                    l.on_fit_start(net)
                self.stopped = False
                for _ in range(start_epoch, epochs):
                    ep = net.epoch
                    # one sampled trace per epoch: dispatch windows hang
                    # off this root (trace ids key on (epoch, window))
                    self._rt = reqtrace.new_trace("train.epoch")
                    with span("fit.epoch", epoch=net.epoch):
                        if self.epoch_start is not None:
                            self.epoch_start()
                        for l in listeners:
                            l.on_epoch_start(net, net.epoch)
                        buf: List = []
                        etl_start = time.perf_counter()
                        for bi, ds in enumerate(iter(iterable)):
                            etl_ms = (time.perf_counter() - etl_start) * 1e3
                            if self.before_batch is not None:
                                ds = self.before_batch(bi, ds)
                                if ds is SKIP:
                                    etl_start = time.perf_counter()
                                    continue
                                if ds is STOP:
                                    self.stopped = True
                                    break
                            fusible = (self.k > 1
                                       and self.fused_step is not None
                                       and self.can_fuse(ds))
                            if fusible and buf and \
                                    batch_signature(buf[0][1]) != \
                                    batch_signature(ds):
                                self._drain(buf)
                                buf = []
                            if fusible:
                                buf.append((bi, ds, etl_ms))
                                if len(buf) == self.k:
                                    self._run_fused(buf)
                                    buf = []
                            else:
                                self._drain(buf)
                                buf = []
                                if self._reshard is not None:
                                    self._witness_batch(ds)
                                t_d = time.perf_counter()
                                loss = self.step(ds)
                                dispatch_ms = (time.perf_counter()
                                               - t_d) * 1e3
                                self._trace_window(bi, bi, dispatch_ms)
                                self._finish(bi, loss, etl_ms, dispatch_ms)
                                if self.after_dispatch is not None:
                                    self.after_dispatch(bi)
                            etl_start = time.perf_counter()
                        self._drain(buf)
                        if self.stopped:
                            self._finish_epoch_trace(ep, stopped=True)
                            break
                        for l in listeners:
                            l.on_epoch_end(net, net.epoch)
                        net.epoch += 1
                        if self.epoch_end is not None:
                            self.epoch_end()
                        # the ONE guaranteed materialization per epoch:
                        # score_ is a float at every epoch boundary
                        # without per-step syncs — and the block boundary
                        # attribution infers device time from
                        net._loss_tracker.materialize()
                    self._finish_epoch_trace(ep)
                for l in listeners:
                    l.on_fit_end(net)
        except BaseException as e:
            # close the epoch trace first so the flight dump's trace
            # block carries the crashed epoch's dispatch windows
            self._finish_epoch_trace(net.epoch, error=type(e).__name__)
            # the crash the flight recorder exists for: dump the ring
            # (recent spans, compiles, device memory) next to the error
            flight.dump("training_exception", exc=e)
            raise
        finally:
            if tracker is not None:
                tracker.on_block = None
        return net

    # ---------------------------------------------------------- helpers
    def _finish_epoch_trace(self, epoch: int, **attrs) -> None:
        """Close the per-epoch trace root (None-safe; resets _rt)."""
        rt, self._rt = self._rt, None
        reqtrace.finish_root(rt, epoch=epoch, iteration=self.net.iteration,
                             steps_per_dispatch=self.k, **attrs)

    def _trace_window(self, bi_lo: int, bi_hi: int, dur_ms: float,
                      fused: bool = False) -> None:
        """Record one train.dispatch span keyed (epoch, step-window).

        dur_ms is the host ENQUEUE time for the window — never a device
        wait, so the span machinery stays sync-free. When the comm
        ledger has priced this owner's compiled programs, the span also
        carries the owner-level collective totals (comm_ops /
        comm_bytes) — host-side metadata from the watchdog, never a
        device read."""
        rt = self._rt
        if rt is None:
            return
        ep = self.net.epoch
        attrs = dict(dur_ms=dur_ms, epoch=ep,
                     window=f"{ep}:{bi_lo}-{bi_hi}",
                     steps=bi_hi - bi_lo + 1, fused=fused)
        comm = self._comm_totals()
        if comm is not None:
            attrs["comm_ops"] = comm["ops"]
            attrs["comm_bytes"] = comm["wire_bytes"]
        reqtrace.record_span(
            rt.trace_id, "train.dispatch", parent_id=rt.span_id, **attrs)

    def _comm_totals(self) -> Optional[dict]:
        """Owner-level compiled-collective totals for the net's active
        jit cache, or None when nothing was priced (ledger disabled,
        probe not fired yet, owner without a WatchedJitCache)."""
        try:
            tag = getattr(self.net._jit_cache, "owner_tag", None)
            if tag is None:
                return None
            return get_watchdog().owner_comm_totals(tag)
        # graft: allow(GL403): span decoration is best-effort by design
        except Exception:
            return None

    def _witness_batch(self, ds) -> None:
        """Reshard-witness seam (commsmon, GL802): before a dispatch,
        compare the batch's COMMITTED shardings against the mesh spine's
        declared batch spec. Metadata-only, and `self._reshard` is None
        whenever commsmon is off, so the hot path pays one attribute
        read."""
        mesh_ctx = self.mesh_ctx
        if mesh_ctx is None:
            return
        from deeplearning4j_tpu.observe.commsmon import check_dispatch_args
        owner = type(self.net).__name__
        spec = mesh_ctx.batch_spec      # leaf -> P(batch_axis, None, ...)
        named = {}
        for field in ("features", "labels"):
            v = getattr(ds, field, None)
            if v is not None:
                named[field] = (v, lambda leaf: spec(leaf.ndim))
        check_dispatch_args(owner, named, witness=self._reshard)

    def _drain(self, buf) -> None:
        """Flush a partial fusion buffer through the per-step path (a
        short tail would need its own K'-sized compile)."""
        for bi, ds, etl_ms in buf:
            if self._reshard is not None:
                self._witness_batch(ds)
            t_d = time.perf_counter()
            loss = self.step(ds)
            dispatch_ms = (time.perf_counter() - t_d) * 1e3
            self._trace_window(bi, bi, dispatch_ms)
            self._finish(bi, loss, etl_ms, dispatch_ms)
            if self.after_dispatch is not None:
                self.after_dispatch(bi)

    def _run_fused(self, buf) -> None:
        if self._reshard is not None:
            self._witness_batch(buf[0][1])
        t_d = time.perf_counter()
        losses = self.fused_step([ds for _, ds, _ in buf])
        # one dispatch for K steps: attribute its enqueue cost evenly
        dispatch_ms = (time.perf_counter() - t_d) * 1e3 / len(buf)
        self._trace_window(buf[0][0], buf[-1][0],
                           dispatch_ms * len(buf), fused=True)
        for j, (bi, ds, etl_ms) in enumerate(buf):
            # losses[j] stays on device — indexing does not sync
            self._finish(bi, losses[j], etl_ms, dispatch_ms)
        if self.after_dispatch is not None:
            # once per scan window: params now reflect all K steps, so a
            # checkpoint here is a consistent (step, rng, cursor) snapshot
            self.after_dispatch(buf[-1][0])

    def _finish(self, bi, loss, etl_ms, dispatch_ms: float = 0.0) -> None:
        net = self.net
        net._loss_tracker.update(loss)
        net.iteration += 1
        self._iter_counter.inc()
        self._etl_hist.observe(etl_ms)
        # host-side dispatch wall time per step: the training-side
        # series the sampler turns into train_dispatch_ms:p99
        self._dispatch_hist.observe(dispatch_ms)
        t_h = time.perf_counter()
        for l in net.listeners:
            if hasattr(l, "set_etl_time"):
                l.set_etl_time(etl_ms)
            l.iteration_done(net, net.iteration, net.epoch,
                             net._loss_tracker.peek())
        if self.after_step is not None:
            self.after_step(bi)
        attr = self._attr
        if attr is not None:
            host_ms = (time.perf_counter() - t_h) * 1e3
            attr.record_iteration(etl_ms, dispatch_ms, host_ms)
