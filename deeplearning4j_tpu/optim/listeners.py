"""Training listeners — the observability seam.

Reference parity: `optimize/api/IterationListener.java` /
`TrainingListener.java` and `optimize/listeners/` (ScoreIterationListener,
PerformanceListener `:60` with samples/sec + ETL time, CollectScores,
TimeIteration). Listeners run on the HOST after each step; because JAX
dispatch is async, reading the score forces a device sync — listeners that
only need it every N iterations therefore only sync every N iterations
(the reference pays a similar cost reading scalars off-device).

Async-dispatch contract (see PERF_NOTES): the `score` passed to
``iteration_done`` is the RAW value off the step — in the deferred-sync
fit path that is a jax device array, not a float. A listener that calls
``float(score)`` (or reads ``model.score_``) pays exactly the host sync it
asks for, stalling the dispatch pipeline for that step; listeners that
don't touch the score (PerformanceListener, TimeIterationListener) cost
nothing. Prefer a ``frequency``/``print_iterations`` cadence ≥10 in hot
loops, or pass ``sync_every=N`` to ``fit()`` to batch materializations.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Reference: `optimize/api/TrainingListener.java` (onEpochStart/
    onEpochEnd/iterationDone; forward/backward hooks collapse into
    iteration_done because the step is one fused XLA computation)."""

    def iteration_done(self, model, iteration: int, epoch: int, score) -> None:
        pass

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass

    def on_fit_start(self, model) -> None:
        pass

    def on_fit_end(self, model) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations. Reference: ScoreIterationListener."""

    def __init__(self, print_iterations: int = 10, out: Optional[Callable] = None):
        self.n = max(1, print_iterations)
        self._out = out or (lambda msg: logger.info(msg))

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.n == 0:
            self._out(f"Score at iteration {iteration} is {float(score):.6f}")


class CollectScoresIterationListener(TrainingListener):
    """Accumulate (iteration, score) pairs. Reference: CollectScoresIterationListener."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class PerformanceListener(TrainingListener):
    """Throughput tracking: samples/sec, batches/sec, ETL time.
    Reference: `optimize/listeners/PerformanceListener.java:24-25,60`."""

    def __init__(self, frequency: int = 10, report: Optional[Callable] = None,
                 *, flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        self.frequency = max(1, frequency)
        self._report = report or (lambda msg: logger.info(msg))
        self._last_time = None
        self._last_iter = 0
        self.last_samples_per_sec = 0.0
        self.last_batches_per_sec = 0.0
        self.last_etl_ms = 0.0
        # MFU reporting (TPU-native extension of the reference's counters):
        # flops_per_step from utils/profiling.step_flops(model, x, y);
        # peak_flops defaults to the chip's spec-sheet bf16 peak — resolved
        # ONCE here, not on the reporting path (the spec lookup + device
        # count don't change mid-fit).
        self.flops_per_step = flops_per_step
        if flops_per_step and peak_flops is None:
            try:
                import jax
                from deeplearning4j_tpu.utils.profiling import peak_flops as \
                    _peak
                # step_flops is the GLOBAL step's HLO count, so the default
                # peak must cover every participating chip. An unknown
                # device kind leaves peak_flops None — peak_flops() warns
                # once naming the kind, and the MFU gauge is OMITTED
                # below instead of publishing NaN.
                per_chip = _peak()
                if per_chip:
                    peak_flops = per_chip * jax.device_count()
            except Exception:
                peak_flops = None
        if peak_flops is not None and not peak_flops > 0:
            peak_flops = None      # NaN/0/negative: same no-gauge path
        self.peak_flops = peak_flops
        self.last_mfu: Optional[float] = None
        self.last_step_ms: Optional[float] = None
        self.last_device_step_ms: Optional[float] = None
        self.last_syncs_per_step: Optional[float] = None
        from deeplearning4j_tpu.observe import get_registry

        reg = get_registry()
        self._g_sps = reg.gauge("train_samples_per_sec")
        self._g_step_ms = reg.gauge("train_step_ms")
        self._g_mfu = reg.gauge("train_mfu")
        self._g_syncs = reg.gauge("train_host_syncs_per_step")

    def set_etl_time(self, ms: float) -> None:
        """Reference: setLastEtlTime threading (`MultiLayerNetwork.java:1092`)."""
        self.last_etl_ms = ms

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            n_batches = iteration - self._last_iter
            bs = getattr(model, "last_batch_size", None) or 0
            self.last_batches_per_sec = n_batches / dt
            self.last_samples_per_sec = n_batches * bs / dt
            self.last_step_ms = dt / n_batches * 1e3
            self._g_sps.set(self.last_samples_per_sec)
            self._g_step_ms.set(self.last_step_ms)
            # measured device step time from the attribution window (the
            # executor parks its StepAttribution on the model) — absent
            # until a window has closed or when attribution is off
            attr = getattr(model, "_attribution", None)
            dev_ms = (attr.last_device_step_ms()
                      if attr is not None else None)
            self.last_device_step_ms = dev_ms
            msg = (f"iteration {iteration}: "
                   f"{self.last_samples_per_sec:.1f} samples/sec, "
                   f"{self.last_batches_per_sec:.2f} batches/sec, "
                   f"{self.last_step_ms:.1f} ms/step, "
                   f"ETL {self.last_etl_ms:.1f} ms")
            if dev_ms:
                msg += f", device {dev_ms:.2f} ms/step"
            if self.flops_per_step and self.peak_flops:
                # MFU over MEASURED device time when attribution has it
                # (wall time charges the device for host stalls); wall
                # step time is the fallback denominator
                step_s = dev_ms / 1e3 if dev_ms else dt / n_batches
                self.last_mfu = (self.flops_per_step / step_s
                                 / self.peak_flops)
                self._g_mfu.set(self.last_mfu)
                msg += (f", MFU {self.last_mfu:.1%}"
                        + (" (device)" if dev_ms else ""))
            from deeplearning4j_tpu.observe import current_monitor

            mon = current_monitor()
            if mon is not None:
                # syncs since the last report window — the runtime version
                # of the perf-guard's dispatch-depth assertion
                self.last_syncs_per_step = mon.take() / n_batches
                self._g_syncs.set(self.last_syncs_per_step)
                msg += f", {self.last_syncs_per_step:.2f} syncs/step"
            self._report(msg)
            self._last_time = now
            self._last_iter = iteration


class TimeIterationListener(TrainingListener):
    """ETA logging. Reference: TimeIterationListener."""

    def __init__(self, total_iterations: int, frequency: int = 100):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self._start = None
        self._start_iter = 0

    def on_fit_start(self, model):
        # the clock starts at fit start, not at the end of the first step —
        # the old lazy init swallowed the first iteration's report and
        # based the rate on a denominator one step too large
        self._start = time.perf_counter()
        self._start_iter = getattr(model, "iteration", 0)

    def iteration_done(self, model, iteration, epoch, score):
        if self._start is None:
            # attached mid-fit (or driven without on_fit_start): anchor the
            # clock one step back so this report still has a rate
            self._start = time.perf_counter()
            self._start_iter = iteration - 1
        if iteration % self.frequency == 0 and iteration > 0:
            done = iteration - self._start_iter
            if done <= 0:
                return
            elapsed = time.perf_counter() - self._start
            rate = elapsed / done
            if self.total and self.total > 0:
                remaining = rate * max(self.total - iteration, 0)
                logger.info(
                    f"iteration {iteration}/{self.total}, "
                    f"ETA {remaining:.0f}s")
            else:
                # total unknown/invalid: report progress without an ETA
                # instead of a nonsense negative estimate
                logger.info(
                    f"iteration {iteration}, {rate * 1e3:.1f} ms/iter")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator. Reference: EvaluativeListener."""

    def __init__(self, iterator, frequency: int = 1, on_epoch: bool = True):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.on_epoch = on_epoch
        self.evaluations: List = []

    def on_epoch_end(self, model, epoch):
        if self.on_epoch and epoch % self.frequency == 0:
            e = model.evaluate(self.iterator)
            self.evaluations.append(e)
            logger.info(f"epoch {epoch} eval: accuracy={e.accuracy():.4f}")
