"""Optimization: updaters (optimizer rules), LR schedules, solver loop.

Reference parity: ND4J `GradientUpdater` impls applied through
`nn/updater/UpdaterBlock.java:101-160` and the solver loop in
`optimize/solvers/BaseOptimizer.java` / `StochasticGradientDescent.java`.
"""

from deeplearning4j_tpu.optim.updaters import (
    Updater, Sgd, Adam, AdaMax, Nadam, AMSGrad, Nesterovs, AdaGrad, AdaDelta,
    RmsProp, NoOp,
)
from deeplearning4j_tpu.optim.schedules import (
    Schedule, FixedSchedule, StepSchedule, ExponentialSchedule, InverseSchedule,
    PolySchedule, SigmoidSchedule, MapSchedule, WarmupCosineSchedule,
)
from deeplearning4j_tpu.optim.solvers import (
    Solver, backtrack_line_search, minimize_cg, minimize_gd, minimize_lbfgs,
)
from deeplearning4j_tpu.optim.executor import LossTracker, TrainingExecutor

__all__ = [
    "Solver", "backtrack_line_search", "minimize_cg", "minimize_gd",
    "minimize_lbfgs", "LossTracker", "TrainingExecutor",
    "Updater", "Sgd", "Adam", "AdaMax", "Nadam", "AMSGrad", "Nesterovs",
    "AdaGrad", "AdaDelta", "RmsProp", "NoOp",
    "Schedule", "FixedSchedule", "StepSchedule", "ExponentialSchedule",
    "InverseSchedule", "PolySchedule", "SigmoidSchedule", "MapSchedule",
    "WarmupCosineSchedule",
]
