"""Full-batch solvers: backtracking line search, nonlinear conjugate
gradient, and L-BFGS.

Reference parity: `optimize/Solver.java:43-64`, `optimize/solvers/
{ConjugateGradient,LBFGS,BackTrackLineSearch}.java` + `BaseOptimizer.java`.
The reference drives these eagerly (one ND4J op at a time, line-search
probes as separate host round-trips); here each solver is ONE jittable
computation over the raveled parameter vector — the whole iteration loop,
line-search probes included, traces into a single XLA program
(`lax.scan` over iterations, `lax.while_loop` for the backtracking), so a
full optimize() is a single device dispatch.

These are batch methods: the loss closure must be deterministic (no
dropout rng), matching the reference's use (full-batch second-order-ish
optimization, e.g. small-data scientific fits and t-SNE's internal
optimizer).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree


def backtrack_line_search(f: Callable[[jnp.ndarray], jnp.ndarray],
                          x: jnp.ndarray, f0, g: jnp.ndarray,
                          d: jnp.ndarray, *, initial_step: float = 1.0,
                          c: float = 1e-4, rho: float = 0.5,
                          max_steps: int = 20):
    """Armijo backtracking: largest alpha in {s, s*rho, s*rho^2, ...} with
    f(x + alpha d) <= f0 + c * alpha * g.d. Returns (alpha, f_new); alpha=0
    (no step) when no trial satisfies the condition — the reference's
    BackTrackLineSearch.java:48 bails the same way after maxIterations.
    Jittable: the probe loop is a `lax.while_loop`."""
    gd = jnp.vdot(g, d)

    def cond(carry):
        alpha, fval, it = carry
        return jnp.logical_and(it < max_steps, fval > f0 + c * alpha * gd)

    def body(carry):
        alpha, _, it = carry
        alpha = alpha * rho
        return alpha, f(x + alpha * d), it + 1

    alpha0 = jnp.asarray(initial_step, x.dtype)
    alpha, fval, it = lax.while_loop(
        cond, body, (alpha0, f(x + alpha0 * d), jnp.asarray(0)))
    ok = fval <= f0 + c * alpha * gd
    return jnp.where(ok, alpha, 0.0), jnp.where(ok, fval, f0)


class SolverResult(NamedTuple):
    x: jnp.ndarray           # final parameter vector
    loss: jnp.ndarray        # final loss
    history: jnp.ndarray     # per-iteration loss trajectory [iterations]


def minimize_cg(f: Callable, x0: jnp.ndarray, *, iterations: int = 100,
                max_line_search: int = 20) -> SolverResult:
    """Polak-Ribiere+ nonlinear conjugate gradient with Armijo line search
    and automatic restart (beta clamped at 0, direction reset when not a
    descent direction). Reference: `optimize/solvers/ConjugateGradient.java`
    (same PR formula + restart-on-non-descent)."""
    vg = jax.value_and_grad(f)
    f0, g0 = vg(x0)

    def step(carry, _):
        x, fval, g, d = carry
        # normalize direction scale so initial_step=1 probes a sane range
        dnorm = jnp.linalg.norm(d)
        d_unit = d / jnp.maximum(dnorm, 1e-12)
        alpha, fnew = backtrack_line_search(
            f, x, fval, g, d_unit, max_steps=max_line_search)
        x_new = x + alpha * d_unit
        fnew, g_new = vg(x_new)
        beta = jnp.maximum(
            jnp.vdot(g_new, g_new - g) / jnp.maximum(jnp.vdot(g, g), 1e-30),
            0.0)  # PR+
        d_new = -g_new + beta * d
        # restart with steepest descent if d_new isn't a descent direction
        d_new = jnp.where(jnp.vdot(d_new, g_new) < 0, d_new, -g_new)
        return (x_new, fnew, g_new, d_new), fnew

    (x, fval, _, _), hist = lax.scan(
        step, (x0, f0, g0, -g0), None, length=iterations)
    return SolverResult(x, fval, hist)


def minimize_lbfgs(f: Callable, x0: jnp.ndarray, *, iterations: int = 100,
                   history: int = 10,
                   max_line_search: int = 20) -> SolverResult:
    """L-BFGS with the standard two-loop recursion over a circular (s, y)
    history and Armijo backtracking. Reference:
    `optimize/solvers/LBFGS.java` (m=4 default there; 10 here).
    Fixed-size buffers keep everything jit-compatible."""
    vg = jax.value_and_grad(f)
    n = x0.shape[0]
    m = history
    f0, g0 = vg(x0)

    S0 = jnp.zeros((m, n), x0.dtype)
    Y0 = jnp.zeros((m, n), x0.dtype)
    rho0 = jnp.zeros((m,), x0.dtype)

    def two_loop(g, S, Y, rho, k):
        """Standard two-loop recursion; entries with rho==0 are inactive."""
        def bwd(i, carry):
            q, a = carry
            idx = jnp.mod(k - 1 - i, m)
            ai = rho[idx] * jnp.vdot(S[idx], q)
            ai = jnp.where(rho[idx] > 0, ai, 0.0)
            q = q - ai * Y[idx]
            return q, a.at[idx].set(ai)

        q, a = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), g.dtype)))
        # initial Hessian scaling gamma = s.y / y.y of the newest pair
        newest = jnp.mod(k - 1, m)
        sy = jnp.vdot(S[newest], Y[newest])
        yy = jnp.vdot(Y[newest], Y[newest])
        gamma = jnp.where(yy > 0, sy / jnp.maximum(yy, 1e-30), 1.0)
        r = gamma * q

        def fwd(i, r):
            idx = jnp.mod(k - m + i, m)
            bi = rho[idx] * jnp.vdot(Y[idx], r)
            corr = (a[idx] - bi) * S[idx]
            return r + jnp.where(rho[idx] > 0, corr, 0.0)

        return lax.fori_loop(0, m, fwd, r)

    def step(carry, _):
        x, fval, g, S, Y, rho, k = carry
        d = -two_loop(g, S, Y, rho, k)
        # fall back to steepest descent if not a descent direction
        d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
        alpha, _ = backtrack_line_search(
            f, x, fval, g, d, max_steps=max_line_search)
        x_new = x + alpha * d
        fnew, g_new = vg(x_new)
        s = x_new - x
        y = g_new - g
        sy = jnp.vdot(s, y)
        # curvature condition: only store useful pairs
        store = sy > 1e-10
        idx = jnp.mod(k, m)
        S = jnp.where(store, S.at[idx].set(s), S)
        Y = jnp.where(store, Y.at[idx].set(y), Y)
        rho = jnp.where(store, rho.at[idx].set(1.0 / jnp.maximum(sy, 1e-30)),
                        rho)
        k = jnp.where(store, k + 1, k)
        return (x_new, fnew, g_new, S, Y, rho, k), fnew

    (x, fval, *_), hist = lax.scan(
        step, (x0, f0, g0, S0, Y0, rho0, jnp.asarray(0)), None,
        length=iterations)
    return SolverResult(x, fval, hist)


def minimize_gd(f: Callable, x0: jnp.ndarray, *, iterations: int = 100,
                max_line_search: int = 20) -> SolverResult:
    """Line (steepest) gradient descent — gradient direction + line search.
    Reference: `optimize/solvers/LineGradientDescent.java`."""
    vg = jax.value_and_grad(f)
    f0, g0 = vg(x0)

    def step(carry, _):
        x, fval, g = carry
        d = -g / jnp.maximum(jnp.linalg.norm(g), 1e-12)
        alpha, _ = backtrack_line_search(
            f, x, fval, g, d, max_steps=max_line_search)
        x_new = x + alpha * d
        fnew, g_new = vg(x_new)
        return (x_new, fnew, g_new), fnew

    (x, fval, _), hist = lax.scan(step, (x0, f0, g0), None, length=iterations)
    return SolverResult(x, fval, hist)


_ALGOS = {
    "conjugate_gradient": minimize_cg,
    "cg": minimize_cg,
    "lbfgs": minimize_lbfgs,
    "line_gradient_descent": minimize_gd,
}


class Solver:
    """Model-level solver driver. Reference: `optimize/Solver.java` —
    builds the optimizer for the model's configured algorithm and runs
    `optimize()` against one (full) batch.

    The model's parameter pytree is raveled into one flat vector (the
    moral equivalent of the reference's flattened params view,
    `MultiLayerNetwork.params()`), minimized, and written back."""

    def __init__(self, model, algo: str = "lbfgs", *, iterations: int = 100,
                 history: int = 10):
        if algo not in _ALGOS:
            raise ValueError(
                f"Unknown solver algorithm {algo!r}; known: {sorted(_ALGOS)}")
        self.model = model
        self.algo = algo
        self.iterations = iterations
        self.history = history
        self._jitted = None
        self._refresh = None

    def optimize(self, features, labels, fmask=None, lmask=None):
        """Run the configured solver to convergence on ONE batch; returns
        the loss trajectory. Deterministic loss (no dropout)."""
        model = self.model
        x0, unravel = ravel_pytree(model.params_tree)
        if features is not None and not isinstance(features,
                                                   (list, tuple, dict)):
            features = jnp.asarray(features)
        if labels is not None and not isinstance(labels, (list, tuple, dict)):
            labels = jnp.asarray(labels)

        minimize = _ALGOS[self.algo]
        kw = {"iterations": self.iterations}
        if self.algo == "lbfgs":
            kw["history"] = self.history

        if self._jitted is None:
            # Masks/states are jit ARGUMENTS (None is a valid empty pytree),
            # not closure captures — each batch's masks and the current BN
            # state are honored, and shape changes retrace naturally.
            def run(flat, feats, labs, fm, lm, states):
                def flat_loss(v):
                    loss, _ = model._loss(unravel(v), states, feats, labs,
                                          fm, lm, None, train=True)
                    return loss
                return minimize(flat_loss, flat, **kw)

            def refresh(flat, feats, labs, fm, lm, states):
                _, ns = model._loss(unravel(flat), states, feats, labs,
                                    fm, lm, None, train=True)
                return ns
            self._jitted = jax.jit(run)
            self._refresh = jax.jit(refresh)
        res = self._jitted(x0, features, labels, fmask, lmask,
                           model.state_tree)
        model.params_tree = unravel(res.x)
        # Persistent layer state (BN running mean/var): the reference's
        # solvers run a train-mode forward per iteration PLUS several
        # line-search probes, decay-blending running stats toward the
        # batch every time — so the blend sees ~4x `iterations` updates,
        # enough for the default 0.9 decay to converge (0.9^40 ≈ 1.5%).
        # Mirror that multiplicity (capped — geometric convergence).
        stateful = getattr(model, "_stateful", set())
        if stateful and model.state_tree:
            states = model.state_tree
            for _ in range(min(4 * self.iterations, 60)):
                ns = self._refresh(res.x, features, labels, fmask, lmask,
                                   states)
                states = {
                    n: (ns[n] if n in stateful and n in ns else states[n])
                    for n in states
                }
            model.state_tree = states
        # graft: allow-sync(final loss readback, once per fit)
        model.score_ = float(res.loss)
        return res.history


def fit_with_solver(model, features, labels, fmasks=None, lmasks=None):
    """Shared non-SGD fit dispatch for MultiLayerNetwork/ComputationGraph:
    cache a Solver on the model (invalidated when the configured algorithm
    or iteration count changes), run one full-batch optimize, return the
    final loss."""
    conf = model.conf
    cached = model._solver
    if (cached is None or cached.algo != conf.optimization_algo
            or cached.iterations != conf.solver_iterations):
        model._solver = Solver(model, conf.optimization_algo,
                               iterations=conf.solver_iterations)
    hist = model._solver.optimize(features, labels, fmasks, lmasks)
    return float(hist[-1])
