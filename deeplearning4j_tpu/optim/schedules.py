"""Learning-rate schedules as pure functions of the iteration counter.

Reference parity: DL4J's `learningRateDecayPolicy` handling
(`NeuralNetConfiguration.java:847-854`: Exponential, Inverse, Poly, Sigmoid,
Step, Schedule map) applied inside `UpdaterBlock.update()`
(`nn/updater/UpdaterBlock.java:116,160`). Here a schedule is
`value(step) -> float` traced into the jitted train step, so LR decay costs
nothing at runtime and stays on-device.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_serde


class Schedule:
    """Base: subclasses implement value(step) with jnp math (jit-safe)."""

    def value(self, step):
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@schedule"] = type(self).__name__
        return d


@register_serde
@dataclasses.dataclass(frozen=True)
class FixedSchedule(Schedule):
    value_: float

    def value(self, step):
        return jnp.asarray(self.value_, jnp.float32)


@register_serde
@dataclasses.dataclass(frozen=True)
class StepSchedule(Schedule):
    """lr * decay_rate^floor(step / step_size). Reference: Step policy."""
    initial: float
    decay_rate: float
    step_size: float

    def value(self, step):
        return self.initial * self.decay_rate ** jnp.floor(step / self.step_size)


@register_serde
@dataclasses.dataclass(frozen=True)
class ExponentialSchedule(Schedule):
    """lr * decay_rate^step. Reference: Exponential policy."""
    initial: float
    decay_rate: float

    def value(self, step):
        return self.initial * self.decay_rate ** jnp.asarray(step, jnp.float32)


@register_serde
@dataclasses.dataclass(frozen=True)
class InverseSchedule(Schedule):
    """lr / (1 + gamma*step)^power. Reference: Inverse policy."""
    initial: float
    gamma: float
    power: float

    def value(self, step):
        return self.initial / (1.0 + self.gamma * step) ** self.power


@register_serde
@dataclasses.dataclass(frozen=True)
class PolySchedule(Schedule):
    """lr * (1 - step/max_iter)^power. Reference: Poly policy."""
    initial: float
    power: float
    max_iter: int

    def value(self, step):
        frac = jnp.clip(step / self.max_iter, 0.0, 1.0)
        return self.initial * (1.0 - frac) ** self.power


@register_serde
@dataclasses.dataclass(frozen=True)
class SigmoidSchedule(Schedule):
    """lr / (1 + exp(-gamma*(step - center))). Reference: Sigmoid policy."""
    initial: float
    gamma: float
    center: int

    def value(self, step):
        return self.initial / (1.0 + jnp.exp(-self.gamma * (step - self.center)))


@register_serde
@dataclasses.dataclass(frozen=True)
class MapSchedule(Schedule):
    """Piecewise-constant from {iteration: lr}. Reference: Schedule map policy
    (`learningRateSchedule`). Implemented branch-free for jit."""
    initial: float
    schedule: Dict[int, float] = dataclasses.field(default_factory=dict)

    def value(self, step):
        # Keys may be str after a JSON round-trip; compare numerically.
        lr = jnp.asarray(self.initial, jnp.float32)
        for k in sorted(self.schedule, key=lambda k: int(k)):
            lr = jnp.where(step >= int(k), self.schedule[k], lr)
        return lr


@register_serde
@dataclasses.dataclass(frozen=True)
class WarmupCosineSchedule(Schedule):
    """Linear warmup then cosine decay — no reference counterpart (modern
    extension; the reference predates warmup-cosine conventions)."""
    peak: float
    warmup_steps: int
    total_steps: int
    final: float = 0.0

    def value(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm = self.peak * step / jnp.maximum(self.warmup_steps, 1)
        frac = jnp.clip(
            (step - self.warmup_steps) / jnp.maximum(self.total_steps - self.warmup_steps, 1),
            0.0, 1.0,
        )
        cos = self.final + 0.5 * (self.peak - self.final) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(step < self.warmup_steps, warm, cos)


def as_schedule(lr) -> Schedule:
    if isinstance(lr, Schedule):
        return lr
    return FixedSchedule(float(lr))
