"""Autoregressive text generation via stateful stepping.

Reference parity: the DL4J text-generation flow samples one token at a
time through `rnnTimeStep` (`zoo/model/TextGenerationLSTM.java` trains
the model; the sampling loop lives in the GravesLSTM character-modelling
example pattern built on `MultiLayerNetwork.rnnTimeStep`). This helper
drives the same contract on this framework's networks and works for
both statefulness mechanisms: LSTM h/c carries and transformer KV
caches (`decode_carry` seeding in `MultiLayerNetwork.rnn_time_step`) —
so a prompt is consumed once and each new token costs one step, not a
full-prefix re-run.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _input_encoding(net) -> str:
    """'ids' for embedding-fronted stacks ([B, T, 1] token ids), 'onehot'
    for vocab-width inputs ([B, T, V])."""
    from deeplearning4j_tpu.nn.layers.feedforward import (
        EmbeddingSequenceLayer,
    )

    return ("ids" if isinstance(net.layers[0], EmbeddingSequenceLayer)
            else "onehot")


def _encode(ids: np.ndarray, encoding: str, vocab: int) -> np.ndarray:
    """ids: [B, T] -> model input [B, T, 1] or one-hot [B, T, V]."""
    if encoding == "ids":
        return ids[..., None].astype(np.float32)
    return np.eye(vocab, dtype=np.float32)[ids]


def generate(net, prompt_ids, n_tokens: int, *, temperature: float = 1.0,
             greedy: bool = False,
             rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Sample `n_tokens` continuations of `prompt_ids` ([B, Tp] ints).

    The network's output layer must produce per-timestep class
    probabilities (softmax). `temperature` rescales them (p^(1/τ),
    renormalized); `greedy` takes the argmax instead of sampling.
    Returns the sampled ids, [B, n_tokens]."""
    prompt_ids = np.asarray(prompt_ids)
    if prompt_ids.ndim == 1:
        prompt_ids = prompt_ids[None, :]
    B = prompt_ids.shape[0]
    vocab = net.layers[-1].n_out
    encoding = _input_encoding(net)
    if rng is None:
        rng = np.random.default_rng(0)

    net.rnn_clear_previous_state()
    out = np.asarray(net.rnn_time_step(_encode(prompt_ids, encoding, vocab)))
    generated = np.empty((B, n_tokens), dtype=np.int64)
    for i in range(n_tokens):
        p = out[:, -1, :].astype(np.float64)
        if greedy:
            tok = p.argmax(axis=-1)
        else:
            if temperature != 1.0:
                p = np.power(np.maximum(p, 1e-30), 1.0 / temperature)
            p = p / p.sum(axis=-1, keepdims=True)
            tok = np.array([rng.choice(vocab, p=p[b]) for b in range(B)])
        generated[:, i] = tok
        if i + 1 < n_tokens:
            out = np.asarray(net.rnn_time_step(
                _encode(tok[:, None], encoding, vocab)))
    return generated
