"""Autoregressive text generation via stateful stepping.

Reference parity: the DL4J text-generation flow samples one token at a
time through `rnnTimeStep` (`zoo/model/TextGenerationLSTM.java` trains
the model; the sampling loop lives in the GravesLSTM character-modelling
example pattern built on `MultiLayerNetwork.rnnTimeStep`). This helper
drives the same contract on this framework's networks and works for
both statefulness mechanisms: LSTM h/c carries and transformer KV
caches (`decode_carry` seeding in `MultiLayerNetwork.rnn_time_step`) —
so a prompt is consumed once and each new token costs one step, not a
full-prefix re-run.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.utils.sampling import (
    SamplingParams, sample_next, sample_token, truncate_probs,
)


def _resolve_net(net):
    """(first_layer, vocab) for a MultiLayerNetwork or a single-input /
    single-output ComputationGraph (the two shapes `rnn_time_step` can
    drive one autoregressive stream through)."""
    if hasattr(net, "layers"):            # MultiLayerNetwork
        return net.layers[0], net.layers[-1].n_out
    conf = getattr(net, "conf", None)
    if conf is None or not hasattr(conf, "network_inputs"):
        raise TypeError(
            f"generate() needs a MultiLayerNetwork or ComputationGraph, "
            f"got {type(net).__name__}")
    if len(conf.network_inputs) != 1 or len(conf.network_outputs) != 1:
        raise ValueError(
            "generate() drives one autoregressive stream: the graph must "
            "have exactly one network input and one output (got "
            f"{list(conf.network_inputs)} -> "
            f"{list(conf.network_outputs)}); drive multi-IO graphs "
            "through rnn_time_step directly")
    # first layer = first layer-bearing vertex downstream of the input
    frontier = {conf.network_inputs[0]}
    first = None
    for name in conf.topological_order:
        if frontier & set(conf.vertex_inputs.get(name, ())):
            lyr = getattr(conf.vertices[name], "layer", None)
            if lyr is not None:
                first = lyr
                break
            frontier.add(name)           # pass-through vertex: keep walking
    if first is None:
        raise ValueError("no layer vertex found downstream of the "
                         "network input")
    out_v = conf.vertices[conf.network_outputs[0]]
    vocab = getattr(getattr(out_v, "layer", None) or out_v, "n_out", None)
    if vocab is None:
        raise ValueError(
            f"output vertex {conf.network_outputs[0]!r} has no n_out; "
            "generate() needs a per-timestep classification head")
    return first, vocab


def _input_encoding(first_layer) -> str:
    """'ids' for embedding-fronted stacks ([B, T, 1] token ids), 'onehot'
    for vocab-width inputs ([B, T, V])."""
    from deeplearning4j_tpu.nn.layers.feedforward import (
        EmbeddingSequenceLayer,
    )

    return ("ids" if isinstance(first_layer, EmbeddingSequenceLayer)
            else "onehot")


def _encode(ids: np.ndarray, encoding: str, vocab: int) -> np.ndarray:
    """ids: [B, T] -> model input [B, T, 1] or one-hot [B, T, V]."""
    if encoding == "ids":
        return ids[..., None].astype(np.float32)
    return np.eye(vocab, dtype=np.float32)[ids]


# Truncation moved to utils/sampling.py so served decode shares the one
# tested implementation; the old private name stays importable.
_truncate = truncate_probs


def _prefill(net, prompt_ids, encoding, vocab, chunk: Optional[int]):
    """Feed the prompt through the stateful stepper, optionally in
    fixed-size chunks (bounds prefill memory; REQUIRED when a
    rolling-cache layer's ring cannot hold the whole prompt in one
    step). Returns the last chunk's output."""
    if chunk is None or prompt_ids.shape[1] <= chunk:
        return np.asarray(net.rnn_time_step(
            _encode(prompt_ids, encoding, vocab)))
    if chunk < 1:
        raise ValueError(f"prefill_chunk must be >= 1, got {chunk}")
    out = None
    for s in range(0, prompt_ids.shape[1], chunk):
        out = np.asarray(net.rnn_time_step(
            _encode(prompt_ids[:, s:s + chunk], encoding, vocab)))
    return out


def generate(net, prompt_ids, n_tokens: int, *, temperature: float = 1.0,
             greedy: bool = False, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             repetition_penalty: float = 1.0,
             prefill_chunk: Optional[int] = None,
             rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Sample `n_tokens` continuations of `prompt_ids` ([B, Tp] ints).

    The network's output layer must produce per-timestep class
    probabilities (softmax). Decoding controls compose in the standard
    order: `repetition_penalty` > 1 suppresses tokens already in the
    prompt or generated so far (probability-space CTRL variant: seen
    tokens' probabilities are raised to that power before
    renormalization), then `temperature` rescales (p^(1/τ)), then
    `top_k` keeps the k most probable tokens, then `top_p` keeps the
    smallest nucleus reaching that cumulative mass; `greedy` takes the
    argmax (after the repetition penalty; the truncation knobs are
    moot). `prefill_chunk` feeds the prompt in chunks of that many
    tokens (bounds prefill memory; lets a rolling-cache net consume
    prompts longer than its ring allows in one step). Returns the
    sampled ids, [B, n_tokens]."""
    prompt_ids = np.asarray(prompt_ids)
    if prompt_ids.ndim == 1:
        prompt_ids = prompt_ids[None, :]
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if repetition_penalty < 1.0:
        raise ValueError(
            f"repetition_penalty must be >= 1, got {repetition_penalty}")
    B = prompt_ids.shape[0]
    first_layer, vocab = _resolve_net(net)
    encoding = _input_encoding(first_layer)
    if rng is None:
        rng = np.random.default_rng(0)
    params = SamplingParams(temperature=temperature, top_k=top_k,
                            top_p=top_p, greedy=greedy)

    penalize = repetition_penalty != 1.0
    if penalize:
        seen = np.zeros((B, vocab), dtype=bool)
        np.put_along_axis(seen, prompt_ids.astype(np.int64) % vocab, True,
                          axis=-1)
    net.rnn_clear_previous_state()
    out = _prefill(net, prompt_ids, encoding, vocab, prefill_chunk)
    generated = np.empty((B, n_tokens), dtype=np.int64)
    for i in range(n_tokens):
        p = out[:, -1, :].astype(np.float64)
        if penalize:
            # floor AFTER the power too: a huge penalty on a small vocab
            # can underflow every seen prob to exactly 0, and once all
            # tokens are seen the renormalization would divide by zero
            p = np.where(seen,
                         np.maximum(np.power(np.maximum(p, 1e-30),
                                             repetition_penalty), 1e-300),
                         p)
            p = p / p.sum(axis=-1, keepdims=True)
        if params.greedy:
            # the one shared implementation (utils/sampling.sample_token)
            # also backs the served fused decode window; greedy here is
            # bit-identical to the numpy path by contract
            tok = np.asarray(sample_token(p, params, None)).astype(np.int64)
        else:
            tok = sample_next(p, params, rng)
        generated[:, i] = tok
        if penalize:
            seen[np.arange(B), tok] = True
        if i + 1 < n_tokens:
            out = np.asarray(net.rnn_time_step(
                _encode(tok[:, None], encoding, vocab)))
    return generated


def beam_search(net, prompt_ids, n_tokens: int, *, beam_width: int = 4,
                length_penalty: float = 0.6,
                eos_id: Optional[int] = None,
                prefill_chunk: Optional[int] = None) -> np.ndarray:
    """Beam-search decoding over the same stateful stepping as
    `generate`. The prompt is prefilled ONCE per batch row; the KV
    caches are then tiled to the beams (`net.rnn_reorder_state`) and
    gathered to each beam's chosen parent on reselection, so no prefix
    is ever recomputed.

    Scores are sum-of-log-probs normalized by the GNMT length penalty
    ((5+len)/6)^alpha with alpha=`length_penalty` (0 disables). With
    `eos_id`, finished beams stop growing (further steps append eos at
    no cost) and the best-scoring finished-or-final beam wins. Returns
    [B, n_tokens] ids (the best beam per batch row, padded with eos
    after finish)."""
    prompt_ids = np.asarray(prompt_ids)
    if prompt_ids.ndim == 1:
        prompt_ids = prompt_ids[None, :]
    if beam_width < 1:
        raise ValueError(f"beam_width must be >= 1, got {beam_width}")
    B = prompt_ids.shape[0]
    W = beam_width
    if n_tokens < 1:
        return np.zeros((B, 0), dtype=np.int64)
    first_layer, vocab = _resolve_net(net)
    encoding = _input_encoding(first_layer)

    net.rnn_clear_previous_state()
    # prefill once per row, then tile the carries to the W beams
    out = _prefill(net, prompt_ids, encoding, vocab, prefill_chunk)
    net.rnn_reorder_state(np.repeat(np.arange(B), W))
    # every beam of a row starts from the same distribution: [B, 1, V]
    # broadcasts against the [B, W] scores
    logp_next = np.log(np.maximum(out[:, -1, :], 1e-30))[:, None, :]

    scores = np.full((B, W), -np.inf)
    scores[:, 0] = 0.0        # identical beams: expand only beam 0 first
    tokens = np.zeros((B, W, n_tokens), dtype=np.int64)
    done = np.zeros((B, W), dtype=bool)
    identity = np.arange(B * W)

    def _norm(s, length):
        if not length_penalty:
            return s
        return s / (((5.0 + length) / 6.0) ** length_penalty)

    for t in range(n_tokens):
        cand = scores[:, :, None] + logp_next            # [B, W, V]
        if eos_id is not None:
            # finished beams extend ONLY with eos, at no cost
            frozen = np.full((vocab,), -np.inf)
            frozen[eos_id] = 0.0
            cand = np.where(done[:, :, None],
                            scores[:, :, None] + frozen[None, None], cand)
        flat = np.broadcast_to(cand, (B, W, vocab)).reshape(B, W * vocab)
        top = np.argsort(-flat, axis=-1, kind="stable")[:, :W]
        parent = top // vocab                            # [B, W]
        tok = top % vocab
        scores = np.take_along_axis(flat, top, axis=-1)
        tokens = np.take_along_axis(
            tokens, parent[:, :, None], axis=1)
        tokens[:, :, t] = tok
        done = np.take_along_axis(done, parent, axis=1)
        if eos_id is not None:
            done = done | (tok == eos_id)
        # reorder the KV caches to the chosen parents (skip the common
        # identity case — a full cache gather per token is pure HBM
        # waste when every beam kept its own parent), then step
        flat_idx = (np.arange(B)[:, None] * W + parent).reshape(-1)
        if not np.array_equal(flat_idx, identity):
            net.rnn_reorder_state(flat_idx)
        if t + 1 < n_tokens and not done.all():
            out = np.asarray(net.rnn_time_step(
                _encode(tok.reshape(-1, 1), encoding, vocab)))
            logp_next = np.log(np.maximum(out[:, -1, :], 1e-30)).reshape(
                B, W, vocab)
    if eos_id is not None:
        finished = (tokens == eos_id).any(-1)
        lengths = np.where(finished,
                           np.argmax(tokens == eos_id, axis=-1) + 1,
                           n_tokens)
    else:
        lengths = np.full((B, W), n_tokens)
    best = np.argmax(_norm(scores, lengths), axis=-1)    # [B]
    return tokens[np.arange(B), best]
