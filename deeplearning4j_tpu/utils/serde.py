"""Config JSON serde — the config-as-data backbone.

Reference parity: DL4J serializes every network configuration to JSON via
Jackson (`nn/conf/NeuralNetConfiguration.java` toJson/fromJson,
`nn/conf/serde/*Deserializer.java` for legacy-format compat). Here every
config object is a frozen dataclass; this module provides a type registry so
nested configs (layers, vertices, schedules, updaters, preprocessors)
round-trip through plain dicts/JSON with a ``@class`` discriminator —
the same polymorphic-JSON pattern Jackson's @JsonTypeInfo gives the reference.

Version compat: `from_dict` tolerates unknown keys (dropped with a warning
hook) so configs written by future versions still load — mirroring the
reference's legacy deserializers (`BaseNetConfigDeserializer.java`).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

_TYPE_REGISTRY: Dict[str, Type] = {}

_TAG = "@class"


def register_serde(cls):
    """Class decorator: register a dataclass for polymorphic JSON round-trip."""
    _TYPE_REGISTRY[cls.__name__] = cls
    return cls


def registered(name: str) -> Type:
    if name not in _TYPE_REGISTRY:
        raise KeyError(
            f"Unknown config class {name!r} — registered: {sorted(_TYPE_REGISTRY)}"
        )
    return _TYPE_REGISTRY[name]


def config_to_dict(obj: Any) -> Any:
    """Recursively convert a (possibly nested) config object to plain data."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {_TAG: type(obj).__name__}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue
            out[f.name] = config_to_dict(v)
        return out
    if isinstance(obj, dict):
        return {k: config_to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [config_to_dict(v) for v in obj]
    if callable(obj) and hasattr(obj, "__name__"):
        # Function-valued fields (custom activations etc.) serialize by name.
        return {"@fn": obj.__name__}
    return obj


def config_from_dict(data: Any) -> Any:
    """Inverse of config_to_dict; tolerant of unknown keys for fwd-compat."""
    if isinstance(data, dict):
        if _TAG in data:
            cls = registered(data[_TAG])
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {}
            for k, v in data.items():
                if k == _TAG:
                    continue
                if k in field_names:
                    kwargs[k] = config_from_dict(v)
                # Unknown keys are dropped (legacy/forward compat).
            return cls(**kwargs)
        if "@fn" in data:
            return data["@fn"]  # resolved lazily by Activation/Loss registries
        return {k: config_from_dict(v) for k, v in data.items()}
    if isinstance(data, list):
        return [config_from_dict(v) for v in data]
    return data


def to_json(obj: Any, indent: int = 2) -> str:
    return json.dumps(config_to_dict(obj), indent=indent, sort_keys=False)


def from_json(s: str) -> Any:
    return config_from_dict(json.loads(s))
