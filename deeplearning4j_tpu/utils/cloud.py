"""Cloud dataset IO + cluster provisioning — deeplearning4j-aws parity.

Reference parity: `deeplearning4j-aws/` (SURVEY §2.7) — `S3Uploader` /
`S3Downloader` move datasets/models through object storage, and
`ClusterSetup`/`ClusterProvision` spin up EC2 worker fleets.

TPU-native redesign:
- Object storage is an SPI (`ObjectStore`). `LocalObjectStore` (filesystem
  directory) always works and is what tests use; `S3ObjectStore` /
  `GCSObjectStore` activate when boto3 / google-cloud-storage exist in the
  environment (neither is baked into this image — constructing them
  without the dependency raises ImportError with a clear message).
- Provisioning: TPU fleets come from the cloud CLI, not an in-process SDK
  loop like EC2. `TpuPodProvisioner` renders the exact `gcloud` command
  lines (create / ssh-run / delete) for a queued-resource v5e slice — the
  ClusterSetup equivalent expressed as auditable commands, optionally
  executed via subprocess when the CLI is present.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional


class ObjectStore:
    """put/get/list over a bucket-like namespace (S3Uploader/Downloader)."""

    def put(self, key: str, local_path: str) -> None:
        raise NotImplementedError

    def get(self, key: str, local_path: str) -> str:
        raise NotImplementedError

    def keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError


class LocalObjectStore(ObjectStore):
    """Directory-backed store — the embedded/test implementation and the
    right answer for single-host and NFS-mounted pod setups."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        root = os.path.abspath(self.root)
        p = os.path.abspath(os.path.join(root, key))
        if p != root and not p.startswith(root + os.sep):
            raise ValueError(f"key escapes store root: {key!r}")
        return p

    def put(self, key: str, local_path: str) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        shutil.copyfile(local_path, dst)

    def get(self, key: str, local_path: str) -> str:
        shutil.copyfile(self._path(key), local_path)
        return local_path

    def keys(self, prefix: str = "") -> List[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                rel = os.path.relpath(os.path.join(dirpath, f), self.root)
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)


class S3ObjectStore(ObjectStore):  # pragma: no cover - env-dependent
    """Reference: `aws/s3/{uploader,reader}`. Requires boto3."""

    def __init__(self, bucket: str):
        try:
            import boto3
        except ImportError as e:
            raise ImportError("S3ObjectStore requires boto3") from e
        self._s3 = boto3.client("s3")
        self.bucket = bucket

    def put(self, key, local_path):
        self._s3.upload_file(local_path, self.bucket, key)

    def get(self, key, local_path):
        self._s3.download_file(self.bucket, key, local_path)
        return local_path

    def keys(self, prefix=""):
        out = []
        paginator = self._s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            out.extend(o["Key"] for o in page.get("Contents", []))
        return out


class GCSObjectStore(ObjectStore):  # pragma: no cover - env-dependent
    """GCS sibling (the natural store next to a TPU pod)."""

    def __init__(self, bucket: str):
        try:
            from google.cloud import storage
        except ImportError as e:
            raise ImportError(
                "GCSObjectStore requires google-cloud-storage") from e
        self._bucket = storage.Client().bucket(bucket)

    def put(self, key, local_path):
        self._bucket.blob(key).upload_from_filename(local_path)

    def get(self, key, local_path):
        self._bucket.blob(key).download_to_filename(local_path)
        return local_path

    def keys(self, prefix=""):
        return [b.name for b in self._bucket.list_blobs(prefix=prefix)]


class TpuPodProvisioner:
    """Reference: `aws/ec2/provision/ClusterSetup.java` — but a TPU fleet
    is declared to the cloud control plane, not SSH-bootstrapped machine by
    machine, so the deliverable is the exact command set."""

    def __init__(self, *, name: str, zone: str = "us-central2-b",
                 accelerator_type: str = "v5litepod-64",
                 runtime_version: str = "tpu-ubuntu2204-base",
                 project: Optional[str] = None):
        self.name = name
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.project = project

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def create_command(self) -> List[str]:
        cmd = self._base() + [
            "create", self.name, f"--zone={self.zone}",
            f"--accelerator-type={self.accelerator_type}",
            f"--version={self.runtime_version}",
        ]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd

    def run_command(self, worker_cmd: str, worker: str = "all") -> List[str]:
        cmd = self._base() + [
            "ssh", self.name, f"--zone={self.zone}", f"--worker={worker}",
            f"--command={worker_cmd}",
        ]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd

    def delete_command(self) -> List[str]:
        cmd = self._base() + ["delete", self.name, f"--zone={self.zone}",
                              "--quiet"]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd

    def execute(self, cmd: List[str]) -> int:  # pragma: no cover - env
        if shutil.which(cmd[0]) is None:
            raise RuntimeError(f"{cmd[0]} CLI not available on this host")
        return subprocess.call(cmd)
