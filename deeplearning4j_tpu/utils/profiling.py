"""Profiling utilities: JAX profiler traces, step FLOP analysis, MFU.

SURVEY §5 tracing gap: the reference has PerformanceListener counters but
"no kernel-level profiler in-repo"; the TPU equivalent named there is
"JAX profiler traces + per-step host metrics" — this module provides
both seams: `trace()` wraps `jax.profiler` (TensorBoard-compatible trace
directories), and `step_flops()` pulls the exact HLO flop count of a
model's compiled train step so listeners can report MFU.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observe.watchdog import note_cost_analysis_failure

logger = logging.getLogger("deeplearning4j_tpu")

# Peak dense bf16 matmul throughput per chip, FLOP/s (public spec sheets).
PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e device_kind is "TPU v5 lite"
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# Peak HBM bandwidth per chip, bytes/s (public spec sheets). Paired with
# PEAK_FLOPS_BY_KIND these define each chip's machine balance (FLOP per
# byte at the roofline ridge) — tools/roofline_report.py joins them
# against watchdog compile costs to rank jit owners by roofline gap.
PEAK_HBM_BYTES_BY_KIND = (
    ("v6", 1.640e12),     # Trillium / v6e
    ("v5p", 2.765e12),
    ("v5 lite", 0.819e12),
    ("v5litepod", 0.819e12),
    ("v5e", 0.819e12),
    ("v5", 2.765e12),
    ("v4", 1.228e12),
    ("v3", 0.900e12),
    ("v2", 0.700e12),
)

# Peak inter-chip interconnect (ICI) bandwidth per chip, bytes/s —
# aggregate across links, one direction (public spec sheets; v5e/v6e
# figures are the 4-link 2D-torus aggregates, v4/v5p the 6-link 3D).
# Paired with the commsmon comm ledger's per-device wire bytes these
# price a program's collective time the way PEAK_HBM prices its memory
# time — tools/comm_report.py joins the two to classify jit owners
# compute-bound vs comm-bound.
PEAK_ICI_BYTES_BY_KIND = (
    ("v6", 0.448e12),     # Trillium / v6e: 4 x ~112 GB/s
    ("v5p", 0.600e12),    # 6 x 100 GB/s
    ("v5 lite", 0.200e12),
    ("v5litepod", 0.200e12),
    ("v5e", 0.200e12),    # 4 x 50 GB/s
    ("v5", 0.600e12),
    ("v4", 0.300e12),     # 6 x 50 GB/s
    ("v3", 0.280e12),
    ("v2", 0.160e12),
)


_warned_kinds: set = set()


def peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Per-chip peak bf16 FLOP/s for a device kind (default: device 0).

    Unknown kinds return None AND warn once naming the kind — callers
    (PerformanceListener) must then OMIT the MFU gauge rather than
    publish NaN, and the warning is the only trace of why."""
    if device_kind is None:
        # spec-sheet lookup keys off the chip model, not placement
        device_kind = jax.devices()[0].device_kind  # graft: allow(GL501): roofline reads device kind only
    kind = device_kind.lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    if kind not in _warned_kinds:
        _warned_kinds.add(kind)
        logger.warning(
            "peak_flops: unrecognized device kind %r — no spec-sheet "
            "peak known, so MFU will not be reported. Add the kind to "
            "PEAK_FLOPS_BY_KIND or pass peak_flops= explicitly.",
            device_kind)
    return None


def peak_hbm_bytes(device_kind: Optional[str] = None) -> Optional[float]:
    """Per-chip peak HBM bandwidth (bytes/s) for a device kind (default:
    device 0). Same contract as `peak_flops`: unknown kinds return None
    and warn once — callers must omit, never fabricate, a roofline."""
    if device_kind is None:
        # spec-sheet lookup keys off the chip model, not placement
        device_kind = jax.devices()[0].device_kind  # graft: allow(GL501): roofline reads device kind only
    kind = device_kind.lower()
    for key, peak in PEAK_HBM_BYTES_BY_KIND:
        if key in kind:
            return peak
    warn_key = ("hbm", kind)
    if warn_key not in _warned_kinds:
        _warned_kinds.add(warn_key)
        logger.warning(
            "peak_hbm_bytes: unrecognized device kind %r — no spec-sheet "
            "bandwidth known. Add the kind to PEAK_HBM_BYTES_BY_KIND or "
            "pass the peak explicitly.", device_kind)
    return None


def peak_ici_bytes(device_kind: Optional[str] = None) -> Optional[float]:
    """Per-chip peak interconnect bandwidth (bytes/s, one direction) for
    a device kind (default: device 0). Same contract as `peak_flops`:
    unknown kinds return None and warn once — callers must omit, never
    fabricate, a comm roofline."""
    if device_kind is None:
        # spec-sheet lookup keys off the chip model, not placement
        device_kind = jax.devices()[0].device_kind  # graft: allow(GL501): roofline reads device kind only
    kind = device_kind.lower()
    for key, peak in PEAK_ICI_BYTES_BY_KIND:
        if key in kind:
            return peak
    warn_key = ("ici", kind)
    if warn_key not in _warned_kinds:
        _warned_kinds.add(warn_key)
        logger.warning(
            "peak_ici_bytes: unrecognized device kind %r — no spec-sheet "
            "interconnect bandwidth known. Add the kind to "
            "PEAK_ICI_BYTES_BY_KIND or pass the peak explicitly.",
            device_kind)
    return None


@dataclasses.dataclass(frozen=True)
class CostReport:
    """XLA cost analysis of one compiled program: compute (flops),
    memory traffic (bytes_accessed) from `cost_analysis()`, and the
    buffer-level footprint from `compiled.memory_analysis()` —
    `peak_memory_bytes` approximates live HBM while the program runs
    (arguments + outputs + XLA temp scratch)."""

    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    peak_memory_bytes: Optional[float] = None
    argument_bytes: Optional[float] = None
    output_bytes: Optional[float] = None
    temp_bytes: Optional[float] = None

    def as_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


def _normalize_cost(cost) -> dict:
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


def step_cost(model, features, labels) -> Optional[CostReport]:
    """Full CostReport for the model's train step: AOT-lower + compile
    the same pure step fn the fit loop jits, then read XLA's cost and
    memory analyses. Failures return None — DEBUG-logged once and
    counted in `profiling_cost_analysis_failures`, never raised."""
    try:
        fn = model.make_step_fn()
        feats = jnp.asarray(features, model.dtype)
        labs = jnp.asarray(labels)
        compiled = jax.jit(fn).lower(
            model.params_tree, model.updater_state, model.state_tree,
            jnp.asarray(0, jnp.int32), feats, labs, None, None,
            jax.random.PRNGKey(0), None).compile()
        cost = _normalize_cost(compiled.cost_analysis())
        arg = out = temp = peak = None
        try:
            mem = compiled.memory_analysis()
        except Exception:
            mem = None
        if mem is not None:
            arg = getattr(mem, "argument_size_in_bytes", None)
            out = getattr(mem, "output_size_in_bytes", None)
            temp = getattr(mem, "temp_size_in_bytes", None)
            if temp is not None:
                peak = float((arg or 0) + (out or 0) + temp)
        return CostReport(
            flops=float(cost.get("flops") or 0.0) or None,
            bytes_accessed=float(cost.get("bytes accessed") or 0.0) or None,
            peak_memory_bytes=peak,
            argument_bytes=arg, output_bytes=out, temp_bytes=temp)
    except Exception as e:
        note_cost_analysis_failure(
            f"step_cost AOT analysis failed: {type(e).__name__}")
        return None


def step_flops(model, features, labels) -> Optional[float]:
    """Exact HLO flop count of the model's train step (AOT cost analysis
    of the same pure step fn the fit loop jits)."""
    report = step_cost(model, features, labels)
    return report.flops if report is not None else None


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace (viewable in TensorBoard / Perfetto).
    The §5 'kernel-level profiler' seam the reference lacked in-repo."""
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


class ProfilerListener:
    """TrainingListener that captures a profiler trace over iterations
    [start_iteration, start_iteration + num_iterations). Attach alongside
    PerformanceListener for numbers + timeline in one run."""

    def __init__(self, log_dir: str, *, start_iteration: int = 5,
                 num_iterations: int = 5):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.num_iterations = num_iterations
        self._active = False
        self.captured = False

    # TrainingListener protocol (duck-typed; no import cycle with optim)
    def on_fit_start(self, model):
        # re-arm: a listener reused across fit() calls captures one
        # trace window per fit, not one per listener lifetime
        self.captured = False

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass

    def iteration_done(self, model, iteration, epoch, score):
        if self.captured:
            return
        if not self._active and iteration >= self.start_iteration:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._started_at = iteration
            self._t0 = time.time()
            return
        if self._active and \
                iteration >= self._started_at + self.num_iterations:
            self._close_trace(iteration)

    def on_fit_end(self, model):
        if self._active:   # fit ended mid-capture: close the trace cleanly
            self._close_trace(getattr(model, "iteration", None))

    def _close_trace(self, end_iteration):
        jax.profiler.stop_trace()
        self._active = False
        self.captured = True
        # Mirror the capture window into the span log so the JSONL
        # timeline can be correlated with the TensorBoard/Perfetto trace.
        from deeplearning4j_tpu.observe import emit_manual_span

        emit_manual_span("jax.profiler.trace", self._t0, time.time(),
                         log_dir=self.log_dir,
                         start_iteration=self._started_at,
                         end_iteration=end_iteration)
