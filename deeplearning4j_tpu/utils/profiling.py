"""Profiling utilities: JAX profiler traces, step FLOP analysis, MFU.

SURVEY §5 tracing gap: the reference has PerformanceListener counters but
"no kernel-level profiler in-repo"; the TPU equivalent named there is
"JAX profiler traces + per-step host metrics" — this module provides
both seams: `trace()` wraps `jax.profiler` (TensorBoard-compatible trace
directories), and `step_flops()` pulls the exact HLO flop count of a
model's compiled train step so listeners can report MFU.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Peak dense bf16 matmul throughput per chip, FLOP/s (public spec sheets).
PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e device_kind is "TPU v5 lite"
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Per-chip peak bf16 FLOP/s for a device kind (default: device 0)."""
    if device_kind is None:
        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    return None


def step_flops(model, features, labels) -> Optional[float]:
    """Exact HLO flop count of the model's train step (AOT cost analysis
    of the same pure step fn the fit loop jits)."""
    fn = model.make_step_fn()
    feats = jnp.asarray(features, model.dtype)
    labs = jnp.asarray(labels)
    try:
        compiled = jax.jit(fn).lower(
            model.params_tree, model.updater_state, model.state_tree,
            jnp.asarray(0, jnp.int32), feats, labs, None, None,
            jax.random.PRNGKey(0), None).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0)) or None
    except Exception:
        return None


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace (viewable in TensorBoard / Perfetto).
    The §5 'kernel-level profiler' seam the reference lacked in-repo."""
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


class ProfilerListener:
    """TrainingListener that captures a profiler trace over iterations
    [start_iteration, start_iteration + num_iterations). Attach alongside
    PerformanceListener for numbers + timeline in one run."""

    def __init__(self, log_dir: str, *, start_iteration: int = 5,
                 num_iterations: int = 5):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.num_iterations = num_iterations
        self._active = False
        self.captured = False

    # TrainingListener protocol (duck-typed; no import cycle with optim)
    def on_fit_start(self, model):
        pass

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass

    def iteration_done(self, model, iteration, epoch, score):
        if self.captured:
            return
        if not self._active and iteration >= self.start_iteration:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._started_at = iteration
            self._t0 = time.time()
            return
        if self._active and \
                iteration >= self._started_at + self.num_iterations:
            self._close_trace(iteration)

    def on_fit_end(self, model):
        if self._active:   # fit ended mid-capture: close the trace cleanly
            self._close_trace(getattr(model, "iteration", None))

    def _close_trace(self, end_iteration):
        jax.profiler.stop_trace()
        self._active = False
        self.captured = True
        # Mirror the capture window into the span log so the JSONL
        # timeline can be correlated with the TensorBoard/Perfetto trace.
        from deeplearning4j_tpu.observe import emit_manual_span

        emit_manual_span("jax.profiler.trace", self._t0, time.time(),
                         log_dir=self.log_dir,
                         start_iteration=self._started_at,
                         end_iteration=end_iteration)
