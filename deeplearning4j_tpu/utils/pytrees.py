"""Pytree parameter helpers — the flattened-view equivalent.

Reference parity: DL4J keeps ALL network parameters in one flat contiguous
INDArray with per-layer views (`MultiLayerNetwork.init():446`,
`initGradientsView():563`; param initializers in `nn/params/`). On TPU the
idiomatic storage is a pytree (dict-of-dicts of jax.Array) — XLA handles
layout; these helpers provide the flat view on demand for serialization,
gradient checks, and parity with `Model.params()` semantics.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np


def flatten_params(tree: Any) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], Any]]:
    """Pytree → (flat 1-D vector, unravel fn). Mirrors `Model.params()`."""
    flat, unravel = jax.flatten_util.ravel_pytree(tree)
    return flat, unravel


def unflatten_params(flat: jnp.ndarray, like: Any) -> Any:
    _, unravel = jax.flatten_util.ravel_pytree(like)
    return unravel(flat)


def param_count(tree: Any) -> int:
    """Reference: `Model.numParams()`."""
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def tree_norm(tree: Any) -> jnp.ndarray:
    """Global L2 norm over all leaves (gradient-norm clipping support)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def tree_map(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


def tree_zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_scale(tree, s):
    return jax.tree_util.tree_map(lambda x: x * s, tree)


def tree_to_numpy(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)
