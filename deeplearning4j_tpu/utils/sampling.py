"""Shared token-sampling kernel for every decode path.

`generate()`/`beam_search()` (utils/textgen.py) and the served decode
sessions (serving/sessions.py) draw next tokens from per-row probability
vectors with the same knobs — temperature, top-k, nucleus top-p, greedy.
This module is the single tested implementation: truncation semantics
(stable-order top-k so k=1 coincides with argmax; the nucleus keeps the
token that crosses the threshold) live here and nowhere else.

Everything is host-side numpy on [B, V] probability matrices — sampling
happens after the device step's output has been fetched, so there is no
tracer anywhere near this code.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def truncate_probs(p: np.ndarray, top_k: Optional[int],
                   top_p: Optional[float]) -> np.ndarray:
    """Nucleus/top-k truncation of a [B, V] probability matrix: zero out
    everything outside the k most probable tokens and/or the smallest
    prefix whose mass reaches top_p (the token crossing the threshold is
    kept, per the nucleus-sampling convention)."""
    if top_k is not None and top_k < p.shape[-1]:
        # exactly k survivors even under ties; stable order on -p makes
        # k=1 coincide with argmax (first occurrence wins)
        order = np.argsort(-p, axis=-1, kind="stable")[:, :top_k]
        keep = np.zeros_like(p, dtype=bool)
        np.put_along_axis(keep, order, True, axis=-1)
        p = np.where(keep, p, 0.0)
    if top_p is not None and top_p < 1.0:
        order = np.argsort(-p, axis=-1)
        sorted_p = np.take_along_axis(p, order, axis=-1)
        csum = np.cumsum(sorted_p, axis=-1)
        # keep tokens strictly before the threshold crossing, plus the
        # crossing token itself (never empty)
        keep_sorted = (csum - sorted_p) < top_p * csum[:, -1:]
        keep = np.zeros_like(p, dtype=bool)
        np.put_along_axis(keep, order, keep_sorted, axis=-1)
        p = np.where(keep, p, 0.0)
    return p


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decoding knobs, validated once at construction time
    (a served request's bad top_p should 400 at admission, not crash a
    shared dispatch mid-stream)."""

    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    greedy: bool = False

    def __post_init__(self):
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.temperature <= 0.0:
            raise ValueError(
                f"temperature must be > 0, got {self.temperature}")


def sample_next(p: np.ndarray, params: SamplingParams,
                rng: np.random.Generator) -> np.ndarray:
    """Draw one token per row from a [B, V] probability matrix.

    Knobs compose in the canonical order `generate()` documents:
    temperature rescales (p^(1/τ), skipped at exactly 1.0 so the default
    path is bit-identical to no-op), then top-k, then top-p, then a
    renormalized categorical draw per row. `greedy` takes the stable
    argmax and ignores the truncation knobs."""
    p = np.asarray(p, dtype=np.float64)
    if p.ndim == 1:
        p = p[None, :]
    if params.greedy:
        return p.argmax(axis=-1)
    if params.temperature != 1.0:
        p = np.power(np.maximum(p, 1e-30), 1.0 / params.temperature)
    p = truncate_probs(p, params.top_k, params.top_p)
    p = p / p.sum(axis=-1, keepdims=True)
    vocab = p.shape[-1]
    return np.array([rng.choice(vocab, p=p[b]) for b in range(p.shape[0])])
