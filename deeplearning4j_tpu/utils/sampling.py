"""Shared token-sampling kernel for every decode path.

`generate()`/`beam_search()` (utils/textgen.py) and the served decode
sessions (serving/sessions.py) draw next tokens from per-row probability
vectors with the same knobs — temperature, top-k, nucleus top-p, greedy.
This module is the single tested implementation: truncation semantics
(stable-order top-k so k=1 coincides with argmax; the nucleus keeps the
token that crosses the threshold) live here and nowhere else.

Two dialects of the same semantics live here:

- host-side numpy (`truncate_probs` / `sample_next`) for paths that
  already fetched the step's output (beam search, legacy generate);
- trace-safe jax (`sample_token` / `sample_token_lanes`) for paths that
  sample *inside* the jitted program — the fused decode window advances
  K tokens per dispatch and cannot afford a host round-trip per draw.

Both dialects share the truncation conventions (stable-order top-k so
k=1 coincides with argmax; the nucleus keeps the token that crosses the
threshold), and the greedy path is bit-identical between them by
contract — `tests/test_fused_decode.py` pins the parity.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def truncate_probs(p: np.ndarray, top_k: Optional[int],
                   top_p: Optional[float]) -> np.ndarray:
    """Nucleus/top-k truncation of a [B, V] probability matrix: zero out
    everything outside the k most probable tokens and/or the smallest
    prefix whose mass reaches top_p (the token crossing the threshold is
    kept, per the nucleus-sampling convention)."""
    if top_k is not None and top_k < p.shape[-1]:
        # exactly k survivors even under ties; stable order on -p makes
        # k=1 coincide with argmax (first occurrence wins)
        order = np.argsort(-p, axis=-1, kind="stable")[:, :top_k]
        keep = np.zeros_like(p, dtype=bool)
        np.put_along_axis(keep, order, True, axis=-1)
        p = np.where(keep, p, 0.0)
    if top_p is not None and top_p < 1.0:
        order = np.argsort(-p, axis=-1)
        sorted_p = np.take_along_axis(p, order, axis=-1)
        csum = np.cumsum(sorted_p, axis=-1)
        # keep tokens strictly before the threshold crossing, plus the
        # crossing token itself (never empty)
        keep_sorted = (csum - sorted_p) < top_p * csum[:, -1:]
        keep = np.zeros_like(p, dtype=bool)
        np.put_along_axis(keep, order, keep_sorted, axis=-1)
        p = np.where(keep, p, 0.0)
    return p


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decoding knobs, validated once at construction time
    (a served request's bad top_p should 400 at admission, not crash a
    shared dispatch mid-stream)."""

    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    greedy: bool = False

    def __post_init__(self):
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.temperature <= 0.0:
            raise ValueError(
                f"temperature must be > 0, got {self.temperature}")


def sample_next(p: np.ndarray, params: SamplingParams,
                rng: np.random.Generator) -> np.ndarray:
    """Draw one token per row from a [B, V] probability matrix.

    Knobs compose in the canonical order `generate()` documents:
    temperature rescales (p^(1/τ), skipped at exactly 1.0 so the default
    path is bit-identical to no-op), then top-k, then top-p, then a
    renormalized categorical draw per row. `greedy` takes the stable
    argmax and ignores the truncation knobs."""
    p = np.asarray(p, dtype=np.float64)
    if p.ndim == 1:
        p = p[None, :]
    if params.greedy:
        return p.argmax(axis=-1)
    if params.temperature != 1.0:
        p = np.power(np.maximum(p, 1e-30), 1.0 / params.temperature)
    p = truncate_probs(p, params.top_k, params.top_p)
    p = p / p.sum(axis=-1, keepdims=True)
    vocab = p.shape[-1]
    return np.array([rng.choice(vocab, p=p[b]) for b in range(p.shape[0])])


# --------------------------------------------------------------------------
# trace-safe dialect: the same knobs as lax ops, usable inside jit/scan
# --------------------------------------------------------------------------

def sample_token_lanes(probs, temperature, top_k, top_p, greedy, keys):
    """Per-lane token draw from a [S, V] probability matrix, trace-safe.

    Every knob is a traced per-lane array so one compiled program serves
    any mix of requests (no per-request specialization, no recompiles on
    session churn):

    - ``temperature`` f32[S]  — 1.0 selects the untouched probabilities
      (same skip-at-exactly-1.0 convention as :func:`sample_next`)
    - ``top_k``       i32[S]  — ``V`` (or more) disables the knob
    - ``top_p``       f32[S]  — 1.0 disables the knob
    - ``greedy``      bool[S] — take the first-occurrence argmax and
      ignore truncation/rng entirely
    - ``keys``        u32[S, 2] — one threefry key per lane; callers
      derive them via ``fold_in(base_key, token_index)`` so draws are
      independent of how many steps share a dispatch (K-invariant)

    Knob order matches ``sample_next``: temperature, top-k, top-p, then
    a renormalized categorical draw. Greedy is bit-identical to the
    numpy path by contract; stochastic draws use jax's threefry stream
    (numpy's Generator is not reproducible on-device, so cross-dialect
    stochastic parity is not promised — K-invariance within this dialect
    is).
    """
    import jax
    import jax.numpy as jnp

    p = probs.astype(jnp.float32)
    greedy_tok = jnp.argmax(p, axis=-1).astype(jnp.int32)

    t = temperature[:, None]
    # temper in log space (softmax(log p / τ) == renormalized p^(1/τ)):
    # float32 underflows p^(1/τ) for cold τ long before float64 does, and
    # every op downstream is scale-invariant, so early renormalization is
    # free. τ == exactly 1.0 selects the untouched probabilities.
    tempered = jax.nn.softmax(jnp.log(jnp.maximum(p, 1e-30)) / t, axis=-1)
    p = jnp.where(t == 1.0, p, tempered)

    # top-k: rank of each token under a stable descending sort; exactly k
    # survivors even under ties (first occurrence wins, like the numpy path)
    order = jnp.argsort(-p, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    p = jnp.where(ranks < top_k[:, None], p, 0.0)

    # top-p on the post-top-k mass: keep tokens whose preceding mass is
    # strictly below the threshold (the crossing token survives, so the
    # nucleus is never empty); top_p == 1.0 keeps every nonzero token
    order = jnp.argsort(-p, axis=-1)
    sorted_p = jnp.take_along_axis(p, order, axis=-1)
    csum = jnp.cumsum(sorted_p, axis=-1)
    keep_sorted = (csum - sorted_p) < top_p[:, None] * csum[:, -1:]
    keep = jnp.take_along_axis(keep_sorted, jnp.argsort(order, axis=-1),
                               axis=-1)
    p = jnp.where(keep, p, 0.0)

    logp = jnp.where(p > 0.0, jnp.log(p), -jnp.inf)
    drawn = jax.vmap(jax.random.categorical)(keys, logp).astype(jnp.int32)
    return jnp.where(greedy, greedy_tok, drawn)


def lane_param_arrays(params_list, vocab):
    """Pack a list of per-lane :class:`SamplingParams` (``None`` for
    inactive lanes) into the array form :func:`sample_token_lanes`
    takes. Disabled knobs use their identity encodings (τ=1, k=V,
    p=1.0); inactive lanes get greedy so they never touch the rng."""
    n = len(params_list)
    temperature = np.ones((n,), np.float32)
    top_k = np.full((n,), int(vocab), np.int32)
    top_p = np.ones((n,), np.float32)
    greedy = np.ones((n,), bool)
    for i, sp in enumerate(params_list):
        if sp is None:
            continue
        temperature[i] = sp.temperature
        top_k[i] = int(vocab) if sp.top_k is None else min(sp.top_k, vocab)
        top_p[i] = 1.0 if sp.top_p is None else sp.top_p
        greedy[i] = bool(sp.greedy)
    return temperature, top_k, top_p, greedy


def sample_token(probs, params: SamplingParams, key):
    """Single-distribution jit-safe sampler over [V] or [B, V] probs,
    sharing :func:`sample_token_lanes` so textgen, single-step decode
    and the fused window all run the one implementation. ``key`` is a
    jax PRNG key (may be ``None`` for greedy). Returns i32 token(s)."""
    import jax
    import jax.numpy as jnp

    p = jnp.asarray(probs)
    squeeze = p.ndim == 1
    if squeeze:
        p = p[None, :]
    b, vocab = p.shape
    if params.greedy:
        tok = jnp.argmax(p, axis=-1).astype(jnp.int32)
        return tok[0] if squeeze else tok
    if key is None:
        raise ValueError("sample_token requires a PRNG key unless greedy")
    temperature, top_k, top_p, greedy = lane_param_arrays([params] * b, vocab)
    keys = jax.random.split(key, b)
    tok = sample_token_lanes(p, jnp.asarray(temperature),
                             jnp.asarray(top_k), jnp.asarray(top_p),
                             jnp.asarray(greedy), keys)
    return tok[0] if squeeze else tok
