"""Shared token-sampling kernel for every decode path.

`generate()`/`beam_search()` (utils/textgen.py) and the served decode
sessions (serving/sessions.py) draw next tokens from per-row probability
vectors with the same knobs — temperature, top-k, nucleus top-p, greedy.
This module is the single tested implementation: truncation semantics
(stable-order top-k so k=1 coincides with argmax; the nucleus keeps the
token that crosses the threshold) live here and nowhere else.

Two dialects of the same semantics live here:

- host-side numpy (`truncate_probs` / `sample_next`) for paths that
  already fetched the step's output (beam search, legacy generate);
- trace-safe jax (`sample_token` / `sample_token_lanes`) for paths that
  sample *inside* the jitted program — the fused decode window advances
  K tokens per dispatch and cannot afford a host round-trip per draw.

Both dialects share the truncation conventions (stable-order top-k so
k=1 coincides with argmax; the nucleus keeps the token that crosses the
threshold), and the greedy path is bit-identical between them by
contract — `tests/test_fused_decode.py` pins the parity.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def truncate_probs(p: np.ndarray, top_k: Optional[int],
                   top_p: Optional[float]) -> np.ndarray:
    """Nucleus/top-k truncation of a [B, V] probability matrix: zero out
    everything outside the k most probable tokens and/or the smallest
    prefix whose mass reaches top_p (the token crossing the threshold is
    kept, per the nucleus-sampling convention)."""
    if top_k is not None and top_k < p.shape[-1]:
        # exactly k survivors even under ties; stable order on -p makes
        # k=1 coincide with argmax (first occurrence wins)
        order = np.argsort(-p, axis=-1, kind="stable")[:, :top_k]
        keep = np.zeros_like(p, dtype=bool)
        np.put_along_axis(keep, order, True, axis=-1)
        p = np.where(keep, p, 0.0)
    if top_p is not None and top_p < 1.0:
        order = np.argsort(-p, axis=-1)
        sorted_p = np.take_along_axis(p, order, axis=-1)
        csum = np.cumsum(sorted_p, axis=-1)
        # keep tokens strictly before the threshold crossing, plus the
        # crossing token itself (never empty)
        keep_sorted = (csum - sorted_p) < top_p * csum[:, -1:]
        keep = np.zeros_like(p, dtype=bool)
        np.put_along_axis(keep, order, keep_sorted, axis=-1)
        p = np.where(keep, p, 0.0)
    return p


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decoding knobs, validated once at construction time
    (a served request's bad top_p should 400 at admission, not crash a
    shared dispatch mid-stream)."""

    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    greedy: bool = False

    def __post_init__(self):
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.temperature <= 0.0:
            raise ValueError(
                f"temperature must be > 0, got {self.temperature}")


def sample_next(p: np.ndarray, params: SamplingParams,
                rng: np.random.Generator) -> np.ndarray:
    """Draw one token per row from a [B, V] probability matrix.

    Knobs compose in the canonical order `generate()` documents:
    temperature rescales (p^(1/τ), skipped at exactly 1.0 so the default
    path is bit-identical to no-op), then top-k, then top-p, then a
    renormalized categorical draw per row. `greedy` takes the stable
    argmax and ignores the truncation knobs."""
    p = np.asarray(p, dtype=np.float64)
    if p.ndim == 1:
        p = p[None, :]
    if params.greedy:
        return p.argmax(axis=-1)
    if params.temperature != 1.0:
        p = np.power(np.maximum(p, 1e-30), 1.0 / params.temperature)
    p = truncate_probs(p, params.top_k, params.top_p)
    p = p / p.sum(axis=-1, keepdims=True)
    vocab = p.shape[-1]
    return np.array([rng.choice(vocab, p=p[b]) for b in range(p.shape[0])])


# --------------------------------------------------------------------------
# trace-safe dialect: the same knobs as lax ops, usable inside jit/scan
# --------------------------------------------------------------------------

def sample_token_lanes(probs, temperature, top_k, top_p, greedy, keys):
    """Per-lane token draw from a [S, V] probability matrix, trace-safe.

    Every knob is a traced per-lane array so one compiled program serves
    any mix of requests (no per-request specialization, no recompiles on
    session churn):

    - ``temperature`` f32[S]  — 1.0 selects the untouched probabilities
      (same skip-at-exactly-1.0 convention as :func:`sample_next`)
    - ``top_k``       i32[S]  — ``V`` (or more) disables the knob
    - ``top_p``       f32[S]  — 1.0 disables the knob
    - ``greedy``      bool[S] — take the first-occurrence argmax and
      ignore truncation/rng entirely
    - ``keys``        u32[S, 2] — one threefry key per lane; callers
      derive them via ``fold_in(base_key, token_index)`` so draws are
      independent of how many steps share a dispatch (K-invariant)

    Knob order matches ``sample_next``: temperature, top-k, top-p, then
    a renormalized categorical draw. Greedy is bit-identical to the
    numpy path by contract; stochastic draws use jax's threefry stream
    (numpy's Generator is not reproducible on-device, so cross-dialect
    stochastic parity is not promised — K-invariance within this dialect
    is).
    """
    import jax
    import jax.numpy as jnp

    p = probs.astype(jnp.float32)
    greedy_tok = jnp.argmax(p, axis=-1).astype(jnp.int32)

    p = warp_probs_lanes(probs, temperature, top_k, top_p)

    logp = jnp.where(p > 0.0, jnp.log(p), -jnp.inf)
    drawn = jax.vmap(jax.random.categorical)(keys, logp).astype(jnp.int32)
    return jnp.where(greedy, greedy_tok, drawn)


def warp_probs_lanes(probs, temperature, top_k, top_p):
    """The truncation half of :func:`sample_token_lanes`, factored out so
    speculative decoding can reason about the *distribution* a stochastic
    lane actually samples from (the rejection rule compares target and
    draft probabilities AFTER temperature/top-k/top-p — warping first
    and applying vanilla rejection sampling to the warped pair is the
    standard distribution-preserving construction). Returns the warped
    [S, V] probabilities, zeroed outside the truncation sets, NOT
    renormalized except by temperature (the ops downstream are
    scale-invariant, same as the sampler). Greedy lanes ignore this
    entirely — they argmax the raw probabilities."""
    import jax
    import jax.numpy as jnp

    p = probs.astype(jnp.float32)
    t = temperature[:, None]
    # temper in log space (softmax(log p / τ) == renormalized p^(1/τ)):
    # float32 underflows p^(1/τ) for cold τ long before float64 does, and
    # every op downstream is scale-invariant, so early renormalization is
    # free. τ == exactly 1.0 selects the untouched probabilities.
    tempered = jax.nn.softmax(jnp.log(jnp.maximum(p, 1e-30)) / t, axis=-1)
    p = jnp.where(t == 1.0, p, tempered)

    # One stable descending sort serves both knobs. Top-k zeroes exactly
    # the tail of the sorted row (ties: first occurrence wins, like the
    # numpy path), which leaves the surviving values in sorted order — so
    # the nucleus cumsum can run in the same space without re-sorting.
    # Sorts dominate this function's cost and it runs per position in
    # every decode dispatch, hence the one-sort formulation.
    order = jnp.argsort(-p, axis=-1)
    sorted_p = jnp.take_along_axis(p, order, axis=-1)
    idx = jnp.arange(p.shape[-1])[None, :]
    sorted_p = jnp.where(idx < top_k[:, None], sorted_p, 0.0)

    # top-p on the post-top-k mass: keep tokens whose preceding mass is
    # strictly below the threshold (the crossing token survives, so the
    # nucleus is never empty); top_p == 1.0 keeps every nonzero token
    csum = jnp.cumsum(sorted_p, axis=-1)
    keep_sorted = (csum - sorted_p) < top_p[:, None] * csum[:, -1:]
    kept_sorted = jnp.where(keep_sorted, sorted_p, 0.0)
    # scatter back to vocabulary order (cheaper than inverting the
    # permutation with another sort)
    return jax.vmap(lambda o, v: jnp.zeros_like(v).at[o].set(v))(
        order, kept_sorted)


def spec_accept_lanes(p_raw, p_warp, q_warp, draft_toks, greedy, uniforms,
                      extra_keys):
    """On-device accept/reject for one speculative-decode window.

    Inputs (S lanes, k draft tokens, V vocab):

    - ``p_raw``   f32[S, k+1, V] — the target's RAW probabilities at each
      chunk position (position i conditions on [t0, d_1..d_i])
    - ``p_warp``  f32[S, k+1, V] — the same, after
      :func:`warp_probs_lanes` (unnormalized is fine)
    - ``q_warp``  f32[S, k, V]   — the draft's warped probabilities each
      ``d_i`` was actually drawn from
    - ``draft_toks`` i32[S, k]
    - ``greedy``  bool[S]
    - ``uniforms`` f32[S, k] — acceptance draws, from a stream
      independent of both models' sampling streams
    - ``extra_keys`` u32[S, 2] — per-lane key for the residual/bonus draw

    Greedy lanes take the longest-prefix fast path: accept ``d_i`` while
    it matches the target's raw argmax; the extra token is the target
    argmax at the first mismatch (the bonus token when everything
    matched). Stochastic lanes run the standard rejection rule — accept
    ``d_i`` with probability ``min(1, p(d_i)/q(d_i))`` on the warped,
    renormalized pair; on the first rejection the replacement is drawn
    from ``normalize(max(p - q, 0))`` (falling back to ``p`` when the
    residual has no mass); full acceptance draws the bonus token from
    the target's last position. Either way every lane yields
    ``n_acc`` accepted draft tokens plus exactly one extra token.

    Returns ``(n_acc i32[S], extra i32[S])``.
    """
    import jax
    import jax.numpy as jnp

    s, k1, _ = p_raw.shape
    k = k1 - 1

    def norm(p):
        return p / jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)

    # --- greedy fast path: longest matching prefix against raw argmax
    tgt_tok = jnp.argmax(p_raw, axis=-1).astype(jnp.int32)      # [S, k+1]
    match = tgt_tok[:, :k] == draft_toks                        # [S, k]
    acc_g = jnp.cumprod(match.astype(jnp.int32), axis=1)
    n_acc_g = acc_g.sum(axis=1).astype(jnp.int32)
    extra_g = jnp.take_along_axis(tgt_tok, n_acc_g[:, None],
                                  axis=1)[:, 0]

    # --- stochastic rejection rule on the warped, renormalized pair
    pn = norm(p_warp)                                           # [S, k+1, V]
    qn = norm(q_warp)                                           # [S, k, V]
    p_d = jnp.take_along_axis(pn[:, :k, :], draft_toks[:, :, None],
                              axis=2)[:, :, 0]                  # [S, k]
    q_d = jnp.take_along_axis(qn, draft_toks[:, :, None],
                              axis=2)[:, :, 0]                  # [S, k]
    ok = uniforms * jnp.maximum(q_d, 1e-30) < p_d               # [S, k]
    acc_s = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    n_acc_s = acc_s.sum(axis=1).astype(jnp.int32)
    # residual at the first rejected position (q padded with zeros at k,
    # so full acceptance falls through to "draw the bonus from p")
    q_pad = jnp.concatenate([qn, jnp.zeros_like(qn[:, :1, :])], axis=1)
    p_at = jnp.take_along_axis(pn, n_acc_s[:, None, None],
                               axis=1)[:, 0, :]                 # [S, V]
    q_at = jnp.take_along_axis(q_pad, n_acc_s[:, None, None],
                               axis=1)[:, 0, :]
    res = jnp.maximum(p_at - q_at, 0.0)
    res = jnp.where((res.sum(axis=-1, keepdims=True) > 0.0), res, p_at)
    logr = jnp.where(res > 0.0, jnp.log(res), -jnp.inf)
    extra_s = jax.vmap(jax.random.categorical)(extra_keys,
                                               logr).astype(jnp.int32)

    n_acc = jnp.where(greedy, n_acc_g, n_acc_s)
    extra = jnp.where(greedy, extra_g, extra_s)
    return n_acc, extra


def lane_param_arrays(params_list, vocab):
    """Pack a list of per-lane :class:`SamplingParams` (``None`` for
    inactive lanes) into the array form :func:`sample_token_lanes`
    takes. Disabled knobs use their identity encodings (τ=1, k=V,
    p=1.0); inactive lanes get greedy so they never touch the rng."""
    n = len(params_list)
    temperature = np.ones((n,), np.float32)
    top_k = np.full((n,), int(vocab), np.int32)
    top_p = np.ones((n,), np.float32)
    greedy = np.ones((n,), bool)
    for i, sp in enumerate(params_list):
        if sp is None:
            continue
        temperature[i] = sp.temperature
        top_k[i] = int(vocab) if sp.top_k is None else min(sp.top_k, vocab)
        top_p[i] = 1.0 if sp.top_p is None else sp.top_p
        greedy[i] = bool(sp.greedy)
    return temperature, top_k, top_p, greedy


def sample_token(probs, params: SamplingParams, key):
    """Single-distribution jit-safe sampler over [V] or [B, V] probs,
    sharing :func:`sample_token_lanes` so textgen, single-step decode
    and the fused window all run the one implementation. ``key`` is a
    jax PRNG key (may be ``None`` for greedy). Returns i32 token(s)."""
    import jax
    import jax.numpy as jnp

    p = jnp.asarray(probs)
    squeeze = p.ndim == 1
    if squeeze:
        p = p[None, :]
    b, vocab = p.shape
    if params.greedy:
        tok = jnp.argmax(p, axis=-1).astype(jnp.int32)
        return tok[0] if squeeze else tok
    if key is None:
        raise ValueError("sample_token requires a PRNG key unless greedy")
    temperature, top_k, top_p, greedy = lane_param_arrays([params] * b, vocab)
    keys = jax.random.split(key, b)
    tok = sample_token_lanes(p, jnp.asarray(temperature),
                             jnp.asarray(top_k), jnp.asarray(top_p),
                             jnp.asarray(greedy), keys)
    return tok[0] if squeeze else tok
