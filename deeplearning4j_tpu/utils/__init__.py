"""Utilities: JSON serde registry, pytree/param-view helpers, dtype policy."""

from deeplearning4j_tpu.utils.serde import register_serde, to_json, from_json, config_to_dict, config_from_dict
from deeplearning4j_tpu.utils.pytrees import flatten_params, unflatten_params, param_count, tree_norm
from deeplearning4j_tpu.utils.timesource import (
    NTPTimeSource, SystemClockTimeSource, TimeSource, TimeSourceProvider,
)
from deeplearning4j_tpu.utils.profiling import (
    ProfilerListener, peak_flops, peak_hbm_bytes, peak_ici_bytes,
    step_flops, trace,
)

__all__ = [
    "register_serde", "to_json", "from_json", "config_to_dict", "config_from_dict",
    "flatten_params", "unflatten_params", "param_count", "tree_norm",
    "TimeSource", "SystemClockTimeSource", "NTPTimeSource",
    "TimeSourceProvider", "ProfilerListener", "peak_flops",
    "peak_ici_bytes",
    "peak_hbm_bytes", "step_flops", "trace",
]
