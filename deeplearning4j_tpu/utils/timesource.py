"""Time sources: system clock + NTP-disciplined clock.

Reference parity: `spark/time/` — `TimeSource` SPI,
`SystemClockTimeSource`, `NTPTimeSource` (queries an NTP server on a
schedule, caches the offset, so phase-timing stats from different hosts
line up on one timeline), selected via `TimeSourceProvider` (system
property `org.deeplearning4j.spark.time.TimeSource`).

The SNTP exchange is the standard 48-byte RFC 4330 client datagram over
UDP — no dependencies. Offline/blocked environments fall back to the
system clock with `synchronized_` False (never an exception at training
time, matching the reference's log-and-continue behavior).
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

_NTP_EPOCH_DELTA = 2208988800  # 1900-01-01 → 1970-01-01 in seconds


class TimeSource:
    """Reference: `spark/time/TimeSource.java`."""

    def current_time_millis(self) -> int:
        raise NotImplementedError


class SystemClockTimeSource(TimeSource):
    """Reference: `spark/time/SystemClockTimeSource.java`."""

    def current_time_millis(self) -> int:
        return int(time.time() * 1000)


def sntp_offset_ms(server: str = "pool.ntp.org", *, port: int = 123,
                   timeout: float = 2.0) -> float:
    """One SNTP exchange → clock offset in ms ((t1-t0)+(t2-t3))/2.
    Raises on network failure (caller decides the fallback policy)."""
    packet = bytearray(48)
    packet[0] = 0x1B  # LI=0, VN=3, Mode=3 (client)
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        t0 = time.time()
        s.sendto(bytes(packet), (server, port))
        data, _ = s.recvfrom(256)
        t3 = time.time()
    if len(data) < 48:
        raise IOError(f"short NTP response from {server}")

    def ts(off):
        sec, frac = struct.unpack("!II", data[off:off + 8])
        return sec - _NTP_EPOCH_DELTA + frac / 2**32

    t1 = ts(32)   # server receive
    t2 = ts(40)   # server transmit
    return (((t1 - t0) + (t2 - t3)) / 2.0) * 1000.0


class NTPTimeSource(TimeSource):
    """Reference: `spark/time/NTPTimeSource.java` — offset measured
    against an NTP server, refreshed every `update_freq_ms`; failures
    leave the last known offset (0 initially) and mark
    `synchronized_ = False`."""

    DEFAULT_SERVER = "0.pool.ntp.org"

    def __init__(self, server: Optional[str] = None,
                 update_freq_ms: int = 30 * 60 * 1000, *,
                 timeout: float = 2.0):
        # reference reads server/frequency from system properties
        self.server = server or os.environ.get(
            "DL4J_TPU_NTP_SERVER", self.DEFAULT_SERVER)
        self.update_freq_ms = update_freq_ms
        self.timeout = timeout
        self.offset_ms = 0.0
        self.synchronized_ = False
        # first sync inline (construction isn't on the timed path); later
        # refreshes run on a daemon thread — the reference schedules its
        # updates on a background executor for the same reason:
        # current_time_millis() must never block on the network.
        self._update_once()
        import threading
        import weakref

        self._stop = threading.Event()
        # The worker holds only a WEAK reference: a bound-method target
        # would pin the instance forever (never GC'd, __del__ never runs,
        # thread leaks). With the weakref the thread exits when the source
        # is dropped OR close()d.
        self._thread = threading.Thread(
            target=_ntp_refresh_worker,
            args=(weakref.ref(self), self._stop),
            daemon=True, name="ntp-refresh")
        self._thread.start()

    def _update_once(self):
        try:
            self.offset_ms = sntp_offset_ms(
                self.server, timeout=self.timeout)
            self.synchronized_ = True
        except Exception:
            # keep last offset; flag unsynchronized (reference logs + keeps
            # serving system time rather than failing training)
            self.synchronized_ = False

    def close(self):
        self._stop.set()

    def __del__(self):
        self._stop.set()

    def current_time_millis(self) -> int:
        """Cached-offset read — never touches the network."""
        return int(time.time() * 1000 + self.offset_ms)


def _ntp_refresh_worker(ref, stop):
    """Module-level refresh loop over a weakref (see NTPTimeSource.__init__).
    Interval clamped to >= 1s so update_freq_ms=0 can't busy-loop SNTP."""
    while True:
        src = ref()
        if src is None:
            return
        interval = max(src.update_freq_ms, 1000) / 1000.0
        del src
        if stop.wait(interval):
            return
        src = ref()
        if src is None:
            return
        src._update_once()
        del src


class TimeSourceProvider:
    """Reference: `spark/time/TimeSourceProvider.java` — singleton chosen
    by config (env var here instead of the JVM system property)."""

    _instance: Optional[TimeSource] = None

    @classmethod
    def get_instance(cls) -> TimeSource:
        if cls._instance is None:
            kind = os.environ.get("DL4J_TPU_TIME_SOURCE", "system").lower()
            cls._instance = (NTPTimeSource() if kind == "ntp"
                             else SystemClockTimeSource())
        return cls._instance

    @classmethod
    def set_instance(cls, ts: Optional[TimeSource]) -> None:
        # no implicit close(): callers may re-register the old instance
        # later (its refresh thread must stay alive); an unreferenced NTP
        # source stops its thread via __del__ when collected
        cls._instance = ts
