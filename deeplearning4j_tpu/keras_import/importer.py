"""Keras JSON-config → native config mapping + weight copying.

Reference parity: `KerasModel.java` (689 LoC, `getComputationGraph():105`),
`KerasSequentialModel.java`, `KerasLayer.java` (1,207 LoC per-type mapping),
entry `KerasModelImport.java:101
(importKerasModelAndWeights)`.

Convention notes (why little transposing happens here): Keras/TF and this
framework share NHWC activations, HWIO conv kernels, [in,out] dense kernels,
and i,f,c,o LSTM gate order — so weights copy through; the reference's NCHW
transposes (`KerasLayer.java` weight-copy paths) are unnecessary.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.keras_import.h5 import Hdf5Archive
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, EmbeddingSequenceLayer, GlobalPoolingLayer, LSTM,
    LastTimeStep, OutputLayer, SimpleRnn, SubsamplingLayer, ZeroPaddingLayer,
)
from deeplearning4j_tpu.models import ComputationGraph, MultiLayerNetwork

_ACT = {
    "relu": "relu", "softmax": "softmax", "sigmoid": "sigmoid",
    "tanh": "tanh", "linear": "identity", "elu": "elu", "selu": "selu",
    "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "swish": "swish", "gelu": "gelu",
    "relu6": "relu6", None: "identity",
}


def _act(cfg: dict, key: str = "activation") -> str:
    a = cfg.get(key)
    if a not in _ACT:
        raise ValueError(f"Unsupported Keras activation {a!r}")
    return _ACT[a]


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _input_type_from_shape(shape) -> Optional[InputType]:
    """batch_input_shape (batch dim first, None) → InputType."""
    if shape is None:
        return None
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(h, w, c)
    raise ValueError(f"Unsupported input shape {shape}")


class _Unsupported(Exception):
    pass


def _map_layer(class_name: str, cfg: dict, *, is_last: bool):
    """One Keras layer config → native layer(s). Reference:
    `KerasLayer.java` per-type mapping."""
    name = cfg.get("name")
    if class_name == "Dense":
        act = _act(cfg)
        if is_last:
            loss = "mcxent" if act == "softmax" else (
                "xent" if act == "sigmoid" else "mse")
            return OutputLayer(name=name, n_out=cfg["units"], activation=act,
                               loss=loss, has_bias=cfg.get("use_bias", True))
        return DenseLayer(name=name, n_out=cfg["units"], activation=act,
                          has_bias=cfg.get("use_bias", True))
    if class_name in ("Conv2D", "Convolution2D"):
        return ConvolutionLayer(
            name=name, n_out=cfg["filters"],
            kernel=_pair(cfg.get("kernel_size", cfg.get("nb_row", 3))),
            stride=_pair(cfg.get("strides", (1, 1))),
            convolution_mode=("same" if cfg.get("padding", "valid") == "same"
                              else "truncate"),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True))
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        return SubsamplingLayer(
            name=name,
            pooling="max" if class_name.startswith("Max") else "avg",
            kernel=_pair(cfg.get("pool_size", (2, 2))),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode=("same" if cfg.get("padding", "valid") == "same"
                              else "truncate"))
    if class_name in ("GlobalAveragePooling2D", "GlobalMaxPooling2D",
                      "GlobalAveragePooling1D", "GlobalMaxPooling1D"):
        return GlobalPoolingLayer(
            name=name,
            pooling="avg" if "Average" in class_name else "max")
    if class_name == "Flatten":
        return None  # handled by automatic CnnToFeedForward preprocessor
    if class_name == "Dropout":
        return DropoutLayer(name=name, dropout=cfg.get("rate", 0.5))
    if class_name == "Activation":
        return ActivationLayer(name=name, activation=_act(cfg))
    if class_name == "BatchNormalization":
        return BatchNormalization(name=name, eps=cfg.get("epsilon", 1e-3),
                                  decay=cfg.get("momentum", 0.99))
    if class_name == "ZeroPadding2D":
        return ZeroPaddingLayer(name=name, pad=_pair(cfg.get("padding", 1)))
    if class_name == "LSTM":
        lstm = LSTM(name=name, n_out=cfg["units"], activation=_act(cfg),
                    gate_activation=_act(cfg, "recurrent_activation"))
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, layer=lstm)
        return lstm
    if class_name == "SimpleRNN":
        rnn = SimpleRnn(name=name, n_out=cfg["units"], activation=_act(cfg))
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, layer=rnn)
        return rnn
    if class_name == "Embedding":
        return EmbeddingSequenceLayer(name=name, n_in=cfg["input_dim"],
                                      n_out=cfg["output_dim"])
    if class_name == "InputLayer":
        return None
    raise _Unsupported(f"Keras layer type {class_name!r} not supported "
                       f"(reference parity list: KerasLayer.java)")


def _copy_weights(net, keras_name: str, our_name: str, weights: List[np.ndarray],
                  layer) -> None:
    """Order conventions per Keras save format (kernel, bias, ...)."""
    if not weights or our_name not in net.params_tree:
        return
    p = dict(net.params_tree[our_name])
    if isinstance(layer, BatchNormalization):
        # keras order: gamma, beta, moving_mean, moving_var
        if len(weights) == 4:
            p["gamma"] = jnp.asarray(weights[0])
            p["beta"] = jnp.asarray(weights[1])
            net.state_tree[our_name] = {
                "mean": jnp.asarray(weights[2]),
                "var": jnp.asarray(weights[3]),
            }
    elif isinstance(layer, (LSTM, SimpleRnn)) or (
            isinstance(layer, LastTimeStep)):
        p["W"] = jnp.asarray(weights[0])
        p["RW"] = jnp.asarray(weights[1])
        if len(weights) > 2:
            p["b"] = jnp.asarray(weights[2])
    else:
        p["W"] = jnp.asarray(weights[0])
        if len(weights) > 1 and "b" in p:
            p["b"] = jnp.asarray(weights[1])
    net.params_tree[our_name] = p


class KerasModelImport:
    """Reference: `KerasModelImport.java` static entry points."""

    @staticmethod
    def import_keras_sequential_model_and_weights(path: str):
        return import_keras_model_and_weights(path)

    @staticmethod
    def import_keras_model_and_weights(path: str):
        return import_keras_model_and_weights(path)


def import_keras_model_and_weights(path: str):
    """Auto-detects Sequential vs functional Model.
    Reference: `KerasModelImport.importKerasModelAndWeights(...):101`."""
    with Hdf5Archive(path) as ar:
        config = ar.model_config()
        cls = config.get("class_name")
        if cls == "Sequential":
            net = _import_sequential(config, ar)
        elif cls in ("Model", "Functional"):
            net = _import_functional(config, ar)
        else:
            raise ValueError(f"Unknown Keras model class {cls!r}")
    return net


def _layer_list(config: dict) -> List[dict]:
    inner = config.get("config")
    if isinstance(inner, list):          # Keras 1
        return inner
    return inner.get("layers", [])       # Keras 2


def _import_sequential(config: dict, ar: Hdf5Archive) -> MultiLayerNetwork:
    """Reference: `KerasSequentialModel.java` → MultiLayerNetwork."""
    klayers = _layer_list(config)
    input_type = None
    layers = []
    keras_names: List[Tuple[str, Any]] = []
    n = len([k for k in klayers
             if k["class_name"] not in ("InputLayer", "Flatten")])
    seen = 0
    for k in klayers:
        cfg = k.get("config", {})
        if input_type is None:
            shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
            it = _input_type_from_shape(shape)
            if it is not None:
                input_type = it
        if k["class_name"] in ("InputLayer", "Flatten"):
            continue
        seen += 1
        layer = _map_layer(k["class_name"], cfg, is_last=(seen == n))
        if layer is None:
            continue
        layers.append(layer)
        keras_names.append((cfg.get("name", k["class_name"]), layer))

    builder = (NeuralNetConfiguration.builder()
               .seed(123)
               .list(*layers))
    if input_type is not None:
        builder = builder.set_input_type(input_type)
    net = MultiLayerNetwork(builder.build()).init()

    h5_names = ar.layer_names()
    for (kname, layer), conf_layer in zip(keras_names, net.conf.layers):
        source = kname if kname in h5_names else None
        if source is None:
            continue
        _copy_weights(net, kname, conf_layer.name, ar.layer_weights(kname),
                      layer)
    return net


def _import_functional(config: dict, ar: Hdf5Archive) -> ComputationGraph:
    """Reference: `KerasModel.getComputationGraph():105`."""
    inner = config["config"]
    klayers = inner["layers"]
    out_names = [o[0] for o in inner.get("output_layers", [])]
    in_names = [i[0] for i in inner.get("input_layers", [])]

    g = NeuralNetConfiguration.builder().seed(123).graph_builder()
    input_types = []
    mapped: Dict[str, Any] = {}
    for k in klayers:
        cname = k["class_name"]
        cfg = k.get("config", {})
        name = k.get("name") or cfg.get("name")
        inbound = k.get("inbound_nodes", [])
        ins: List[str] = []
        if inbound:
            node = inbound[0]
            if isinstance(node, dict):  # Keras 3 style
                args = node.get("args", [])
                def walk(a):
                    if isinstance(a, dict) and "config" in a and \
                            "keras_history" in a.get("config", {}):
                        ins.append(a["config"]["keras_history"][0])
                    elif isinstance(a, (list, tuple)):
                        for x in a:
                            walk(x)
                walk(args)
            else:
                for entry in node:
                    ins.append(entry[0])
        if cname == "InputLayer":
            g.add_inputs(name)
            it = _input_type_from_shape(
                cfg.get("batch_input_shape") or cfg.get("batch_shape"))
            input_types.append(it)
            continue
        if cname == "Add":
            g.add_vertex(name, ElementWiseVertex(op="add"), *ins)
            continue
        if cname in ("Concatenate", "Merge"):
            g.add_vertex(name, MergeVertex(), *ins)
            continue
        if cname == "Average":
            g.add_vertex(name, ElementWiseVertex(op="avg"), *ins)
            continue
        if cname == "Multiply":
            g.add_vertex(name, ElementWiseVertex(op="mul"), *ins)
            continue
        if cname == "Flatten":
            from deeplearning4j_tpu.nn.graph import PreprocessorVertex
            from deeplearning4j_tpu.nn.preprocessors import CnnToFeedForward
            g.add_vertex(name, PreprocessorVertex(
                preprocessor=CnnToFeedForward()), *ins)
            continue
        layer = _map_layer(cname, cfg, is_last=(name in out_names))
        if layer is None:
            continue
        mapped[name] = layer
        g.add_layer(name, layer, *ins)
    g.set_outputs(*out_names)
    if input_types and all(t is not None for t in input_types):
        g.set_input_types(*input_types)
    net = ComputationGraph(g.build()).init()

    h5_names = set(ar.layer_names())
    for name, layer in mapped.items():
        if name in h5_names:
            _copy_weights(net, name, name, ar.layer_weights(name), layer)
    return net
