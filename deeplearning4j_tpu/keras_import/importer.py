"""Keras JSON/YAML-config → native config mapping + weight copying.

Reference parity: `KerasModel.java` (689 LoC, `getComputationGraph():105`),
`KerasSequentialModel.java`, `KerasLayer.java` (1,207 LoC per-type mapping),
entry `KerasModelImport.java:48-192` (importKerasModelAndWeights +
importKerasModelConfiguration from JSON/YAML).

Convention notes (why little transposing happens here): Keras/TF and this
framework share NHWC activations, HWIO conv kernels, [in,out] dense kernels,
and i,f,c,o LSTM gate order — so most weights copy through; the reference's
NCHW transposes (`KerasLayer.java` weight-copy paths) are unnecessary. The
exceptions handled below: depthwise kernels ([kh,kw,in,mult] → [kh,kw,1,
in*mult] for feature_group_count grouping) and GRU gate order (Keras z,r,h →
ours r,z,n).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.keras_import.h5 import Hdf5Archive
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, Bidirectional, ConvolutionLayer,
    Convolution1DLayer, Cropping2DLayer, Deconvolution2DLayer, DenseLayer,
    DepthwiseConvolution2DLayer, DropoutLayer, EmbeddingSequenceLayer,
    GlobalPoolingLayer, GRU, LSTM, LastTimeStep, OutputLayer, PReLULayer,
    SeparableConvolution2DLayer, SimpleRnn, SubsamplingLayer,
    Subsampling1DLayer, Upsampling2DLayer, ZeroPaddingLayer,
)
from deeplearning4j_tpu.models import ComputationGraph, MultiLayerNetwork

_ACT = {
    "relu": "relu", "softmax": "softmax", "sigmoid": "sigmoid",
    "tanh": "tanh", "linear": "identity", "elu": "elu", "selu": "selu",
    "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "swish": "swish", "gelu": "gelu",
    "silu": "silu", "mish": "mish", "leaky_relu": "leakyrelu",
    "relu6": "relu6", "exponential": "exp", None: "identity",
}

# Keras initializer (class or Keras-1 string) → native WeightInit name.
# Reference: KerasLayer.java mapWeightInitialization.
_INIT_MAP = {
    "glorotuniform": "xavier_uniform", "glorotnormal": "xavier",
    "henormal": "relu", "heuniform": "relu_uniform",
    "lecunnormal": "lecun_normal", "lecununiform": "lecun_uniform",
    "zeros": "zero", "zero": "zero", "ones": "ones", "one": "ones",
    "randomnormal": "normal", "normal": "normal",
    "randomuniform": "uniform", "uniform": "uniform",
    "truncatednormal": "normal", "orthogonal": "orthogonal",
    "identity": "identity",
}


def _act(cfg: dict, key: str = "activation") -> str:
    a = cfg.get(key)
    if isinstance(a, dict):  # Keras 3 serialized activation object
        a = a.get("config", {}).get("name", a.get("class_name", "")).lower()
    if a not in _ACT:
        raise ValueError(f"Unsupported Keras activation {a!r}")
    return _ACT[a]


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _first(v, default=1):
    if isinstance(v, (list, tuple)):
        return v[0] if v else default
    return v if v is not None else default


def _winit_name(cfg: dict, key: str = "kernel_initializer") -> Optional[str]:
    """Keras initializer → native weight_init (None keeps the default)."""
    init = cfg.get(key, cfg.get("init"))
    if init is None:
        return None
    if isinstance(init, dict):
        cname = init.get("class_name", "")
        c = init.get("config", {}) or {}
        if cname == "VarianceScaling":
            mode = c.get("mode", "fan_in")
            dist = str(c.get("distribution", "normal"))
            scale = float(c.get("scale", 1.0))
            uni = "uniform" in dist
            if mode == "fan_avg":
                return "xavier_uniform" if uni else "xavier"
            if mode == "fan_in" and scale >= 2.0:
                return "relu_uniform" if uni else "relu"
            return "lecun_uniform" if uni else "lecun_normal"
        k = cname.lower().replace("_", "")
    else:
        k = str(init).lower().replace("_", "")
    return _INIT_MAP.get(k)


def _l1l2(cfg: dict, *keys) -> Tuple[Optional[float], Optional[float]]:
    """Extract (l1, l2) from a Keras regularizer config dict."""
    for key in keys:
        r = cfg.get(key)
        if isinstance(r, dict):
            c = r.get("config", r)
            l1 = float(c.get("l1") or 0.0) or None
            l2 = float(c.get("l2") or 0.0) or None
            return l1, l2
    return None, None


def _common(cfg: dict) -> dict:
    """Weight-init + regularizer fields shared by parameterized layers.
    Reference: KerasLayer.java getWeightRegularizerFromConfig /
    mapWeightInitialization."""
    l1, l2 = _l1l2(cfg, "kernel_regularizer", "W_regularizer")
    l1b, l2b = _l1l2(cfg, "bias_regularizer", "b_regularizer")
    out = {}
    wi = _winit_name(cfg)
    if wi is not None:
        out["weight_init"] = wi
    if l1 is not None:
        out["l1"] = l1
    if l2 is not None:
        out["l2"] = l2
    if l1b is not None:
        out["l1_bias"] = l1b
    if l2b is not None:
        out["l2_bias"] = l2b
    return out


def _input_type_from_shape(shape) -> Optional[InputType]:
    """batch_input_shape (batch dim first, None) → InputType."""
    if shape is None:
        return None
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(h, w, c)
    raise ValueError(f"Unsupported input shape {shape}")


class _Unsupported(Exception):
    pass


def _conv_mode(cfg: dict) -> str:
    pad = cfg.get("padding", cfg.get("border_mode", "valid"))
    if pad == "causal":
        raise _Unsupported(
            "Keras padding='causal' (left-padded temporal conv) has no "
            "native counterpart yet")
    return "same" if pad == "same" else "truncate"


def _map_layer(class_name: str, cfg: dict, *, is_last: bool):
    """One Keras layer config → native layer(s). Reference:
    `KerasLayer.java` per-type mapping (1,207 LoC of the same dispatch)."""
    name = cfg.get("name")
    common = _common(cfg)
    if class_name == "Dense":
        act = _act(cfg)
        units = cfg.get("units", cfg.get("output_dim"))
        if is_last:
            loss = "mcxent" if act == "softmax" else (
                "xent" if act == "sigmoid" else "mse")
            return OutputLayer(name=name, n_out=units, activation=act,
                               loss=loss, has_bias=cfg.get("use_bias", True),
                               **common)
        return DenseLayer(name=name, n_out=units, activation=act,
                          has_bias=cfg.get("use_bias", True), **common)
    if class_name in ("Conv2D", "Convolution2D"):
        return ConvolutionLayer(
            name=name, n_out=cfg.get("filters", cfg.get("nb_filter")),
            kernel=_pair(cfg.get("kernel_size",
                                 (cfg.get("nb_row", 3), cfg.get("nb_col", 3)))),
            stride=_pair(cfg.get("strides", cfg.get("subsample", (1, 1)))),
            dilation=_pair(cfg.get("dilation_rate", (1, 1))),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True), **common)
    if class_name in ("Conv1D", "Convolution1D"):
        return Convolution1DLayer(
            name=name, n_out=cfg.get("filters", cfg.get("nb_filter")),
            kernel=_first(cfg.get("kernel_size", cfg.get("filter_length", 3)), 3),
            stride=_first(cfg.get("strides", cfg.get("subsample_length", 1))),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True), **common)
    if class_name == "SeparableConv2D":
        return SeparableConvolution2DLayer(
            name=name, n_out=cfg["filters"],
            depth_multiplier=cfg.get("depth_multiplier", 1),
            kernel=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", (1, 1))),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True), **common)
    if class_name == "DepthwiseConv2D":
        return DepthwiseConvolution2DLayer(
            name=name, depth_multiplier=cfg.get("depth_multiplier", 1),
            kernel=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", (1, 1))),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True), **common)
    if class_name in ("Conv2DTranspose", "Deconvolution2D"):
        return Deconvolution2DLayer(
            name=name, n_out=cfg["filters"],
            kernel=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", (1, 1))),
            convolution_mode=_conv_mode(cfg),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True), **common)
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        return SubsamplingLayer(
            name=name,
            pooling="max" if class_name.startswith("Max") else "avg",
            kernel=_pair(cfg.get("pool_size", (2, 2))),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode=_conv_mode(cfg))
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        return Subsampling1DLayer(
            name=name,
            pooling="max" if class_name.startswith("Max") else "avg",
            kernel=_first(cfg.get("pool_size", cfg.get("pool_length", 2)), 2),
            stride=_first(cfg.get("strides") or cfg.get("stride")
                          or cfg.get("pool_size", 2), 2),
            convolution_mode=_conv_mode(cfg))
    if class_name in ("GlobalAveragePooling2D", "GlobalMaxPooling2D",
                      "GlobalAveragePooling1D", "GlobalMaxPooling1D"):
        return GlobalPoolingLayer(
            name=name,
            pooling="avg" if "Average" in class_name else "max")
    if class_name == "Flatten":
        return None  # handled by automatic CnnToFeedForward preprocessor
    if class_name in ("Dropout", "SpatialDropout1D", "SpatialDropout2D",
                      "GaussianDropout", "AlphaDropout"):
        return DropoutLayer(name=name, dropout=cfg.get("rate", 0.5))
    if class_name == "GaussianNoise":
        return None  # identity at inference; regularization-only layer
    if class_name == "Activation":
        return ActivationLayer(name=name, activation=_act(cfg))
    if class_name == "LeakyReLU":
        a = cfg.get("alpha", cfg.get("negative_slope", 0.3))
        return ActivationLayer(name=name, activation=f"leakyrelu:{float(a)}")
    if class_name == "ELU":
        return ActivationLayer(
            name=name, activation=f"elu:{float(cfg.get('alpha', 1.0))}")
    if class_name == "ThresholdedReLU":
        return ActivationLayer(
            name=name,
            activation=f"thresholdedrelu:{float(cfg.get('theta', 1.0))}")
    if class_name == "ReLU":
        mv = cfg.get("max_value")
        ns = float(cfg.get("negative_slope") or 0.0)
        th = float(cfg.get("threshold") or 0.0)
        if th or (ns and mv is not None):
            raise _Unsupported(
                f"Keras ReLU with threshold={th}/negative_slope={ns}/"
                f"max_value={mv} combination not supported")
        if ns:
            return ActivationLayer(name=name, activation=f"leakyrelu:{ns}")
        if mv is None:
            return ActivationLayer(name=name, activation="relu")
        return ActivationLayer(
            name=name,
            activation="relu6" if mv == 6 else f"clippedrelu:{float(mv)}")
    if class_name == "Softmax":
        return ActivationLayer(name=name, activation="softmax")
    if class_name == "PReLU":
        return PReLULayer(name=name)  # alpha shape preserved at weight copy
    if class_name == "BatchNormalization":
        return BatchNormalization(name=name, eps=cfg.get("epsilon", 1e-3),
                                  decay=cfg.get("momentum", 0.99),
                                  scale=cfg.get("scale", True),
                                  center=cfg.get("center", True))
    if class_name == "ZeroPadding2D":
        return ZeroPaddingLayer(name=name, pad=_pair(cfg.get("padding", 1)))
    if class_name == "Cropping2D":
        return Cropping2DLayer(name=name, crop=_pair(cfg.get("cropping", 0)))
    if class_name == "UpSampling2D":
        return Upsampling2DLayer(name=name, size=_pair(cfg.get("size", 2)))
    if class_name == "LSTM":
        lstm = LSTM(name=name, n_out=cfg.get("units", cfg.get("output_dim")),
                    activation=_act(cfg),
                    gate_activation=_act(cfg, "recurrent_activation"),
                    **common)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, layer=lstm)
        return lstm
    if class_name == "GRU":
        reset_after = bool(cfg.get("reset_after", False))
        gru = GRU(name=name, n_out=cfg.get("units", cfg.get("output_dim")),
                  activation=_act(cfg),
                  gate_activation=_act(cfg, "recurrent_activation"),
                  reset_after=reset_after, recurrent_bias=reset_after,
                  **common)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, layer=gru)
        return gru
    if class_name == "SimpleRNN":
        rnn = SimpleRnn(name=name,
                        n_out=cfg.get("units", cfg.get("output_dim")),
                        activation=_act(cfg), **common)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(name=name, layer=rnn)
        return rnn
    if class_name == "Bidirectional":
        inner_cfg = cfg["layer"]
        inner = _map_layer(inner_cfg["class_name"],
                           dict(inner_cfg.get("config", {})), is_last=False)
        pooled = isinstance(inner, LastTimeStep)
        core = inner.layer if pooled else inner
        # return_sequences=False is handled by Bidirectional itself (forward
        # last step + backward full-sequence state), NOT LastTimeStep — the
        # backward half's Keras semantics align with t=0, not t=T-1.
        return Bidirectional(name=name, layer=core,
                             merge=(cfg.get("merge_mode") or "concat"),
                             return_sequences=not pooled)
    if class_name == "Embedding":
        return EmbeddingSequenceLayer(name=name, n_in=cfg["input_dim"],
                                      n_out=cfg["output_dim"])
    if class_name == "InputLayer":
        return None
    raise _Unsupported(f"Keras layer type {class_name!r} not supported "
                       f"(reference parity list: KerasLayer.java)")


def _gru_perm(arr: np.ndarray, h: int) -> np.ndarray:
    """Keras GRU gate order z,r,h → native r,z,n (last-axis blocks)."""
    z, r, n = arr[..., :h], arr[..., h:2 * h], arr[..., 2 * h:]
    return np.concatenate([r, z, n], axis=-1)


def _rnn_param_block(layer, weights: List[np.ndarray]) -> Dict[str, Any]:
    """kernel/recurrent/bias triple → native param dict for one direction.
    When the file has no bias (Keras use_bias=False), the bias is ZEROED —
    the native init's forget-gate bias of 1 would otherwise shift every
    gate vs the Keras model, which has no bias term at all."""
    p: Dict[str, Any] = {}
    nb = weights[0].shape[-1]
    if isinstance(layer, GRU):
        h = layer.n_out
        p["W"] = jnp.asarray(_gru_perm(weights[0], h))
        p["RW"] = jnp.asarray(_gru_perm(weights[1], h))
        if len(weights) > 2:
            b = weights[2]
            if b.ndim == 2:  # reset_after: [input_bias, recurrent_bias]
                p["b"] = jnp.asarray(_gru_perm(b[0], h))
                p["rb"] = jnp.asarray(_gru_perm(b[1], h))
            else:
                p["b"] = jnp.asarray(_gru_perm(b, h))
        else:
            p["b"] = jnp.zeros((nb,), jnp.float32)
            if layer.recurrent_bias:
                p["rb"] = jnp.zeros((nb,), jnp.float32)
    else:
        p["W"] = jnp.asarray(weights[0])
        p["RW"] = jnp.asarray(weights[1])
        p["b"] = (jnp.asarray(weights[2]) if len(weights) > 2
                  else jnp.zeros((nb,), jnp.float32))
    return p


def _copy_weights(net, keras_name: str, our_name: str,
                  weights: List[np.ndarray], layer) -> None:
    """Order conventions per Keras save format (kernel, bias, ...)."""
    if not weights or our_name not in net.params_tree:
        return
    if isinstance(layer, LastTimeStep):
        layer = layer.layer
    p = dict(net.params_tree[our_name])
    if isinstance(layer, BatchNormalization):
        # keras order: gamma, beta, moving_mean, moving_var; the layer's
        # scale/center flags (carried from the Keras config by _map_layer)
        # say which of gamma/beta are present in the file.
        w = list(weights)
        expected = 2 + int(layer.scale) + int(layer.center)
        if len(w) != expected:
            raise ValueError(
                f"BatchNormalization '{keras_name}': {len(w)} weight arrays "
                f"but scale={layer.scale}/center={layer.center} imply "
                f"{expected}")
        if layer.scale:
            p["gamma"] = jnp.asarray(w.pop(0))
        if layer.center:
            p["beta"] = jnp.asarray(w.pop(0))
        net.state_tree[our_name] = {
            "mean": jnp.asarray(w[0]),
            "var": jnp.asarray(w[1]),
        }
    elif isinstance(layer, Bidirectional):
        # merge over the init dicts so params absent from the file (e.g. a
        # zero bias when the inner RNN has use_bias=False) survive
        half = len(weights) // 2
        p["fwd"] = {**p.get("fwd", {}),
                    **_rnn_param_block(layer.layer, weights[:half])}
        p["bwd"] = {**p.get("bwd", {}),
                    **_rnn_param_block(layer.layer, weights[half:])}
    elif isinstance(layer, (LSTM, GRU, SimpleRnn)):
        p.update(_rnn_param_block(layer, weights))
    elif isinstance(layer, SeparableConvolution2DLayer):
        dk = weights[0]  # [kh, kw, in, mult]
        p["dW"] = jnp.asarray(dk.reshape(dk.shape[0], dk.shape[1], 1, -1))
        p["pW"] = jnp.asarray(weights[1])
        if len(weights) > 2 and "b" in p:
            p["b"] = jnp.asarray(weights[2])
    elif isinstance(layer, DepthwiseConvolution2DLayer):
        dk = weights[0]
        p["W"] = jnp.asarray(dk.reshape(dk.shape[0], dk.shape[1], 1, -1))
        if len(weights) > 1 and "b" in p:
            p["b"] = jnp.asarray(weights[1])
    elif isinstance(layer, PReLULayer):
        # Keras alpha shape = input shape minus batch, with 1s on
        # shared_axes (e.g. (1,1,C) for shared_axes=[1,2], (H,W,C) for the
        # default). Any of these broadcast correctly against [B, ..., C] in
        # PReLULayer.apply, so keep the shape; ravel only plain vectors.
        a = weights[0]
        p["alpha"] = jnp.asarray(a if a.ndim > 1 else np.ravel(a))
    else:
        p["W"] = jnp.asarray(weights[0])
        if len(weights) > 1 and "b" in p:
            p["b"] = jnp.asarray(weights[1])
    net.params_tree[our_name] = p


class KerasModelImport:
    """Reference: `KerasModelImport.java` static entry points."""

    @staticmethod
    def import_keras_sequential_model_and_weights(path: str):
        return import_keras_model_and_weights(path)

    @staticmethod
    def import_keras_model_and_weights(path: str):
        return import_keras_model_and_weights(path)

    @staticmethod
    def import_keras_model_configuration(path_or_str: str):
        return import_keras_configuration(path_or_str)


def import_keras_model_and_weights(path: str):
    """Auto-detects Sequential vs functional Model.
    Reference: `KerasModelImport.importKerasModelAndWeights(...):101`."""
    with Hdf5Archive(path) as ar:
        config = ar.model_config()
        cls = config.get("class_name")
        if cls == "Sequential":
            net = _import_sequential(config, ar)
        elif cls in ("Model", "Functional"):
            net = _import_functional(config, ar)
        else:
            raise ValueError(f"Unknown Keras model class {cls!r}")
    return net


def import_keras_configuration(text: str):
    """Config-only import (random weights) from a JSON or YAML string or a
    .json/.yaml file path. Reference:
    `KerasModelImport.importKerasModelConfiguration` /
    `importKerasSequentialConfiguration` (JSON + YAML entry points)."""
    import os

    if os.path.exists(text):
        with open(text) as f:
            text = f.read()
    config = None
    try:
        config = json.loads(text)
    except (json.JSONDecodeError, ValueError):
        import yaml

        config = yaml.safe_load(text)
    if not isinstance(config, dict) or "class_name" not in config:
        raise ValueError("Not a Keras model configuration (JSON or YAML)")
    cls = config["class_name"]
    if cls == "Sequential":
        return _import_sequential(config, None)
    if cls in ("Model", "Functional"):
        return _import_functional(config, None)
    raise ValueError(f"Unknown Keras model class {cls!r}")


def _layer_list(config: dict) -> List[dict]:
    inner = config.get("config")
    if isinstance(inner, list):          # Keras 1
        return inner
    return inner.get("layers", [])       # Keras 2


def _import_sequential(config: dict,
                       ar: Optional[Hdf5Archive]) -> MultiLayerNetwork:
    """Reference: `KerasSequentialModel.java` → MultiLayerNetwork."""
    klayers = _layer_list(config)
    input_type = None
    layers = []
    keras_names: List[Tuple[str, Any]] = []
    n = len([k for k in klayers
             if k["class_name"] not in ("InputLayer", "Flatten")])
    seen = 0
    for k in klayers:
        cfg = k.get("config", {})
        if input_type is None:
            shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
            it = _input_type_from_shape(shape)
            if it is not None:
                input_type = it
        if k["class_name"] in ("InputLayer", "Flatten"):
            continue
        seen += 1
        layer = _map_layer(k["class_name"], cfg, is_last=(seen == n))
        if layer is None:
            continue
        layers.append(layer)
        keras_names.append((cfg.get("name", k["class_name"]), layer))

    builder = (NeuralNetConfiguration.builder()
               .seed(123)
               .list(*layers))
    if input_type is not None:
        builder = builder.set_input_type(input_type)
    net = MultiLayerNetwork(builder.build()).init()

    if ar is not None:
        h5_names = ar.layer_names()
        for (kname, layer), conf_layer in zip(keras_names, net.conf.layers):
            if kname not in h5_names:
                continue
            _copy_weights(net, kname, conf_layer.name,
                          ar.layer_weights(kname), layer)
    return net


def _import_functional(config: dict,
                       ar: Optional[Hdf5Archive]) -> ComputationGraph:
    """Reference: `KerasModel.getComputationGraph():105`."""
    inner = config["config"]
    klayers = inner["layers"]
    out_names = [o[0] for o in inner.get("output_layers", [])]
    in_names = [i[0] for i in inner.get("input_layers", [])]

    g = NeuralNetConfiguration.builder().seed(123).graph_builder()
    input_types = []
    mapped: Dict[str, Any] = {}
    for k in klayers:
        cname = k["class_name"]
        cfg = k.get("config", {})
        name = k.get("name") or cfg.get("name")
        inbound = k.get("inbound_nodes", [])
        ins: List[str] = []
        if inbound:
            node = inbound[0]
            if isinstance(node, dict):  # Keras 3 style
                args = node.get("args", [])
                def walk(a):
                    if isinstance(a, dict) and "config" in a and \
                            "keras_history" in a.get("config", {}):
                        ins.append(a["config"]["keras_history"][0])
                    elif isinstance(a, (list, tuple)):
                        for x in a:
                            walk(x)
                walk(args)
            else:
                for entry in node:
                    ins.append(entry[0])
        if cname == "InputLayer":
            g.add_inputs(name)
            it = _input_type_from_shape(
                cfg.get("batch_input_shape") or cfg.get("batch_shape"))
            input_types.append(it)
            continue
        if cname == "Add":
            g.add_vertex(name, ElementWiseVertex(op="add"), *ins)
            continue
        if cname == "Subtract":
            g.add_vertex(name, ElementWiseVertex(op="sub"), *ins)
            continue
        if cname == "Concatenate":
            g.add_vertex(name, MergeVertex(), *ins)
            continue
        if cname == "Merge":  # Keras 1 merge with a mode string
            mode = cfg.get("mode", "concat")
            if mode in ("concat", "concatenate"):
                g.add_vertex(name, MergeVertex(), *ins)
            elif mode == "sum":
                g.add_vertex(name, ElementWiseVertex(op="add"), *ins)
            elif mode == "mul":
                g.add_vertex(name, ElementWiseVertex(op="mul"), *ins)
            elif mode == "ave":
                g.add_vertex(name, ElementWiseVertex(op="avg"), *ins)
            else:
                raise _Unsupported(f"Keras Merge mode {mode!r}")
            continue
        if cname == "Average":
            g.add_vertex(name, ElementWiseVertex(op="avg"), *ins)
            continue
        if cname == "Multiply":
            g.add_vertex(name, ElementWiseVertex(op="mul"), *ins)
            continue
        if cname == "Maximum":
            g.add_vertex(name, ElementWiseVertex(op="max"), *ins)
            continue
        if cname == "Flatten":
            from deeplearning4j_tpu.nn.graph import PreprocessorVertex
            from deeplearning4j_tpu.nn.preprocessors import CnnToFeedForward
            g.add_vertex(name, PreprocessorVertex(
                preprocessor=CnnToFeedForward()), *ins)
            continue
        layer = _map_layer(cname, cfg, is_last=(name in out_names))
        if layer is None:
            continue
        mapped[name] = layer
        g.add_layer(name, layer, *ins)
    g.set_outputs(*out_names)
    if input_types and all(t is not None for t in input_types):
        g.set_input_types(*input_types)
    net = ComputationGraph(g.build()).init()

    if ar is not None:
        h5_names = set(ar.layer_names())
        for name, layer in mapped.items():
            if name in h5_names:
                _copy_weights(net, name, name, ar.layer_weights(name), layer)
    return net
