"""HDF5 archive access for Keras checkpoints.

Reference parity: `Hdf5Archive.java:22-35` (JavaCPP hdf5 → h5py here):
model config JSON from root attrs, per-layer weight groups under
`model_weights/` (Keras 2) or the root (Keras 1).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np


class Hdf5Archive:
    def __init__(self, path: str):
        import h5py

        self._f = h5py.File(path, "r")

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    @staticmethod
    def _decode(v) -> str:
        if isinstance(v, bytes):
            return v.decode("utf-8")
        return str(v)

    def model_config(self) -> dict:
        """The training config JSON (reference: readAttributeAsJson)."""
        if "model_config" not in self._f.attrs:
            raise ValueError("No 'model_config' attribute — not a Keras "
                             "model file saved with model.save()")
        return json.loads(self._decode(self._f.attrs["model_config"]))

    def keras_version(self) -> str:
        root = self._weights_root()
        for holder in (self._f, root):
            if holder is not None and "keras_version" in holder.attrs:
                return self._decode(holder.attrs["keras_version"])
        return "1"

    def _weights_root(self):
        if "model_weights" in self._f:
            return self._f["model_weights"]
        return self._f

    def layer_names(self) -> List[str]:
        root = self._weights_root()
        if "layer_names" in root.attrs:
            return [self._decode(n) for n in root.attrs["layer_names"]]
        return list(root.keys())

    def layer_weights(self, layer_name: str) -> List[np.ndarray]:
        """Ordered weight arrays for a layer (kernel first, then bias...)."""
        root = self._weights_root()
        if layer_name not in root:
            return []
        grp = root[layer_name]
        if "weight_names" in grp.attrs:
            names = [self._decode(n) for n in grp.attrs["weight_names"]]
        else:
            names = []

            def collect(g, prefix=""):
                for k in g:
                    item = g[k]
                    if hasattr(item, "keys"):
                        collect(item, prefix + k + "/")
                    else:
                        names.append(prefix + k)
            collect(grp)
        out = []
        for n in names:
            node = grp
            for part in n.split("/"):
                if part in node:
                    node = node[part]
            out.append(np.asarray(node))
        return out
