"""Keras .h5 model import.

Reference parity: deeplearning4j-modelimport (`KerasModelImport.java:48-192`,
`KerasModel.java`, `KerasLayer.java`, `Hdf5Archive.java`) — parse the Keras
JSON config stored in the HDF5 file, map layers to native configs
(Sequential → MultiLayerNetwork, functional Model → ComputationGraph), and
copy weights with convention transposes. The reference reads HDF5 through
JavaCPP JNI bindings; here h5py plays that role.
"""

from deeplearning4j_tpu.keras_import.importer import (
    KerasModelImport, import_keras_configuration,
    import_keras_model_and_weights,
)
from deeplearning4j_tpu.keras_import.h5 import Hdf5Archive

__all__ = ["KerasModelImport", "import_keras_configuration",
           "import_keras_model_and_weights", "Hdf5Archive"]
