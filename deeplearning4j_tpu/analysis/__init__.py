"""graft-lint — tracer-safety & recompile-hazard static analysis.

The static counterpart of the runtime observability core: the
RecompileWatchdog and HostSyncMonitor (observe/) catch jit-cache churn
and host syncs *after* they ship; this package catches the patterns
that cause them at review time, over plain ASTs. The analyzer modules
are stdlib-only — linting never traces, compiles, or touches a device.

    python -m deeplearning4j_tpu.analysis deeplearning4j_tpu tests \
        --strict --baseline .graftlint-baseline.json

Public API:

    lint_paths(paths) / lint_file(path) / lint_source(src) -> [Finding]
    RULES                         — rule registry (id -> Rule)
    RULES_VERSION                 — bumped on rule-semantics changes;
                                    invalidates the result cache
    RUNTIME_RULE_HINTS            — runtime-event kind -> static rules
                                    (the watchdog/monitor/lockmon/
                                    donatemon cross-check)
    load_baseline/apply_baseline/write_baseline/prune_baseline
    Program / CallGraph           — whole-program call graph (callgraph.py)
    analyze_lock_program/sources/paths      — GL7xx lockset pass
    analyze_shardflow_program/sources/paths — GL8xx sharding/donation
                                              dataflow pass
    lint_files_cached             — (mtime, sha) result cache over
                                    `.graftlint-cache.json` (cache.py)
"""

from deeplearning4j_tpu.analysis.baseline import (   # noqa: F401
    apply_baseline, load_baseline, prune_baseline, write_baseline,
)
from deeplearning4j_tpu.analysis.cache import (      # noqa: F401
    CACHE_FILE, lint_files_cached,
)
from deeplearning4j_tpu.analysis.callgraph import (  # noqa: F401
    CallGraph, Program,
)
from deeplearning4j_tpu.analysis.engine import (     # noqa: F401
    DEFAULT_HOT_PREFIXES, Finding, is_hot, lint_file, lint_files,
    lint_paths, lint_source,
)
from deeplearning4j_tpu.analysis.locks import (      # noqa: F401
    analyze_lock_paths, analyze_lock_program, analyze_lock_sources,
)
from deeplearning4j_tpu.analysis.rules import (      # noqa: F401
    RULES, RULES_VERSION, RUNTIME_RULE_HINTS, Rule, runtime_hint,
)
from deeplearning4j_tpu.analysis.shardflow import (  # noqa: F401
    analyze_shardflow_paths, analyze_shardflow_program,
    analyze_shardflow_sources,
)

__all__ = [
    "CACHE_FILE", "CallGraph", "DEFAULT_HOT_PREFIXES", "Finding",
    "Program", "RULES", "RULES_VERSION", "RUNTIME_RULE_HINTS", "Rule",
    "analyze_lock_paths", "analyze_lock_program", "analyze_lock_sources",
    "analyze_shardflow_paths", "analyze_shardflow_program",
    "analyze_shardflow_sources", "apply_baseline", "is_hot", "lint_file",
    "lint_files", "lint_files_cached", "lint_paths", "lint_source",
    "load_baseline", "prune_baseline", "runtime_hint", "write_baseline",
]
