"""CLI for graft-lint.

    python -m deeplearning4j_tpu.analysis [paths...]
        [--format text|json|sarif] [--strict]
        [--baseline FILE] [--write-baseline FILE] [--prune-baseline]
        [--select GL2,GL301] [--ignore GL4] [--list-rules]
        [--hot-prefix PREFIX ...] [--changed [BASE]] [--no-cache]

Exit codes: 0 clean (after baseline/suppressions); 1 findings
(errors only by default, any finding under --strict); 2 usage error.
`tools/ci_check.sh` runs `--strict --baseline .graftlint-baseline.json`
as the repo's lint-clean gate.

Results are cached in `.graftlint-cache.json` (per-file mtime/sha +
whole-program digest; invalidated by RULES_VERSION bumps) so repeat
runs over an unchanged tree are stat-only. `--no-cache` forces a cold
pass and leaves the cache file untouched.

`--prune-baseline` rewrites the baseline file (default
`.graftlint-baseline.json`, or `--baseline FILE`) dropping entries
that no longer match any current finding, prints what was pruned, and
exits 0 — run it after fixing baselined findings so the debt ledger
never overstates what is still allowed.

`--changed [BASE]` lints only the .py files `git diff --name-only BASE`
reports (default BASE: HEAD), plus untracked .py files — the pre-commit
path, which skips the whole-repo call-graph build the GL7xx lockset
pass otherwise pays. Positional paths become a filter: a changed file
is linted only if it lies under one of them. Exit-code semantics are
UNCHANGED: 0/1/2 mean exactly what they mean without --changed, and no
changed files means 0 findings means exit 0.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

from deeplearning4j_tpu.analysis.baseline import (
    apply_baseline, load_baseline, prune_baseline, write_baseline,
)
from deeplearning4j_tpu.analysis.cache import CACHE_FILE
from deeplearning4j_tpu.analysis.engine import (
    DEFAULT_HOT_PREFIXES, iter_python_files, lint_paths,
)
from deeplearning4j_tpu.analysis.report import RENDERERS
from deeplearning4j_tpu.analysis.rules import ERROR, RULES


def _split_rules(csv: Optional[str]) -> Optional[List[str]]:
    if not csv:
        return None
    return [s.strip() for s in csv.split(",") if s.strip()]


def _changed_files(base: str, roots: List[str]) -> List[str]:
    """Changed .py files vs `base` (plus untracked), filtered to the
    requested roots (no roots = keep everything). Raises RuntimeError
    when git itself fails."""
    def _git(*cmd: str) -> List[str]:
        proc = subprocess.run(["git", *cmd], capture_output=True,
                              text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(cmd)} failed: "
                f"{proc.stderr.strip() or proc.returncode}")
        return [ln.strip() for ln in proc.stdout.splitlines()
                if ln.strip()]

    changed = set(_git("diff", "--name-only", base, "--"))
    changed |= set(_git("ls-files", "--others", "--exclude-standard"))
    norm_roots = [r.rstrip("/").replace(os.sep, "/") for r in roots]
    out = []
    for f in sorted(changed):
        if not f.endswith(".py") or not os.path.isfile(f):
            continue            # deleted files show in the diff too
        norm = f.replace(os.sep, "/")
        if not norm_roots or any(norm == r or norm.startswith(r + "/")
                                 for r in norm_roots):
            out.append(f)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="graft-lint: tracer-safety & recompile-hazard "
                    "static analysis")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories (default: "
                         "deeplearning4j_tpu; under --changed, "
                         "default: no path filter)")
    ap.add_argument("--format", choices=sorted(RENDERERS),
                    default="text")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on ANY un-baselined finding "
                         "(default: errors only)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="subtract findings recorded in FILE")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write the current findings to FILE and exit 0")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries that no longer match "
                         "any current finding (clamping counts), print "
                         "what was pruned, and exit 0; uses --baseline "
                         "FILE or .graftlint-baseline.json")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the .graftlint-cache.json result "
                         "cache (cold re-lint; cache file untouched)")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule-id prefixes to keep "
                         "(e.g. GL2,GL301)")
    ap.add_argument("--ignore", metavar="RULES",
                    help="comma-separated rule-id prefixes to drop")
    ap.add_argument("--hot-prefix", action="append", default=None,
                    metavar="PREFIX",
                    help="override the hot-module path prefixes "
                         "(repeatable)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="BASE",
                    help="lint only files changed vs BASE (git diff "
                         "--name-only; default HEAD) plus untracked .py "
                         "files; positional paths act as a filter; "
                         "exit-code semantics unchanged")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{r.id} [{r.category}/{r.severity}] {r.name}: "
                  f"{r.summary}")
        return 0

    hot = tuple(args.hot_prefix) if args.hot_prefix \
        else DEFAULT_HOT_PREFIXES
    if args.changed is not None:
        try:
            paths = _changed_files(args.changed, args.paths)
        except RuntimeError as e:
            print(f"graft-lint: {e}", file=sys.stderr)
            return 2
    else:
        paths = args.paths or ["deeplearning4j_tpu"]
    files = iter_python_files(paths)
    cache_path = None if args.no_cache else CACHE_FILE
    findings = lint_paths(paths, hot_prefixes=hot,
                          select=_split_rules(args.select),
                          ignore=_split_rules(args.ignore),
                          cache_path=cache_path)

    if args.prune_baseline:
        bpath = args.baseline or ".graftlint-baseline.json"
        try:
            doc, pruned = prune_baseline(findings, bpath)
        except (OSError, ValueError, KeyError) as e:
            print(f"graft-lint: cannot prune baseline {bpath}: {e}",
                  file=sys.stderr)
            return 2
        for e in pruned:
            print(f"graft-lint: pruned {e['rule']} {e['path']} "
                  f"(-{e['dropped']} of {e['count']}): "
                  f"{e['snippet'][:60]}")
        print(f"graft-lint: pruned {len(pruned)} stale baseline "
              f"entr{'y' if len(pruned) == 1 else 'ies'}; "
              f"{len(doc['findings'])} remain in {bpath}")
        return 0

    if args.write_baseline:
        doc = write_baseline(findings, args.write_baseline)
        print(f"graft-lint: wrote {len(doc['findings'])} baseline "
              f"entr{'y' if len(doc['findings']) == 1 else 'ies'} "
              f"({len(findings)} finding(s)) to {args.write_baseline}")
        return 0

    baselined = 0
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"graft-lint: cannot load baseline "
                  f"{args.baseline}: {e}", file=sys.stderr)
            return 2
        findings, baselined = apply_baseline(findings, baseline)

    out = RENDERERS[args.format](findings, files=len(files),
                                 baselined=baselined)
    sys.stdout.write(out)

    if args.strict:
        return 1 if findings else 0
    return 1 if any(f.severity == ERROR for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
