"""Interprocedural sharding & donation dataflow — the GL8xx family.

The lockset pass (analysis/locks.py) made concurrency bugs
machine-checkable; this pass does the same for the three dataflow
properties that kill sharded jax programs, over the same whole-program
call graph (analysis/callgraph.py):

  donated   — a value passed at a `donate_argnums` position of a jitted
              call is DEAD afterwards: XLA may alias its buffer into
              the output. Donating callables are discovered from
              `@partial(jax.jit, donate_argnums=...)` decorators,
              `name = jax.jit(f, donate_argnums=...)` bindings (local,
              module-global, and `self.attr = ...` class attributes),
              immediately-invoked jit calls, and functions that RETURN
              a donating callable (`self._step = self._build_step()`),
              and donation flows through resolved helper calls: a
              helper that forwards its parameter into a donated slot
              kills its caller's argument too.
  placement — which `with_sharding_constraint`/`device_put` site a
              value's spec came from. Two values with *textually
              different* specs combined in one binop/concat/stack mean
              GSPMD inserts an implicit resharding collective at the
              combine point.
  device    — the engine's host-side device taint (`_devicey`),
              followed to serialization sinks. `np.asarray()` /
              `jax.device_get()` launder the taint, exactly as the
              sync rules model it; the taint also flows through
              resolved helpers whose parameter reaches a sink.

Rules (CAT_SHARDING):

  GL801 use-after-donate [error]        — read/pass of a donated value
        after the donating call, incl. through resolved helpers.
        Related location: the donating call site.
  GL802 cross-spec-combine [warn]       — operands with differing
        placement provenance combined. Related: both placement sites.
  GL803 jit-pytree-churn [warn]         — one jitted callee invoked
        with differing literal pytree structure across call sites
        (same dict keys in a different order, or list-vs-tuple of the
        same length — same leaves, different treedef, silent
        recompile). Related: the other call site.
  GL804 device-value-serialized [error] — device taint reaching
        json.dumps/pickle/struct.pack/b64encode/.tobytes() without
        laundering. Related (helper case): the sink inside the helper.
  GL805 collective-axis-literal [warn]  — psum/all_gather/ppermute/...
        axis given as a string literal outside parallel/mesh.py.

Soundness posture mirrors locks.py: facts only come from code the call
graph actually resolves, so an unresolved dynamic call never invents a
donation — GL801/GL804 fire only on provable flows. Loop bodies are
walked twice so a loop-carried use-after-donate (`for b: loss =
step(params, b)` with donated `params`) is caught; `if`/`else` arms
fork the dead-set and merge may-dead, so mutually-exclusive branches
don't poison each other. The same-statement reassignment idiom
(`self.params, self.opt_state, loss = self._step(self.params, ...)`)
is clean by construction: the call's arguments are read (and the
donation recorded) before the assignment targets re-bind the names.

Suppression uses the engine grammar (`# graft: allow(GL80x): reason`);
runtime cross-check is observe/donatemon.py (`DL4J_TPU_DONATEMON=1`),
whose events carry the same GL801 rule id and buffer names, so static
and runtime findings are string-comparable (tools/donatemon_smoke.py
asserts it).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.callgraph import (
    MAX_PROPAGATION_ROUNDS, CallGraph, FunctionInfo, ModuleInfo, Program,
)
from deeplearning4j_tpu.analysis.engine import (
    DEFAULT_HOT_PREFIXES, Finding, _collect_suppressions, _Ctx,
    _FileLinter, _Imports, _terminal, suppression_covers,
)

#: Terminals that retag placement: x = with_sharding_constraint(v, SPEC)
_PLACEMENT_FUNCS = frozenset({"with_sharding_constraint", "device_put"})

#: Combining callables (beyond BinOp) that materialize both operands
#: under ONE spec — a cross-spec call forces a reshard of the odd one.
_COMBINE_FUNCS = frozenset({
    "concatenate", "stack", "hstack", "vstack", "einsum", "matmul",
    "dot", "tensordot", "where", "add", "multiply",
})

#: Collectives whose axis argument is a mesh-axis name (GL805), mapped
#: to the positional index the axis occupies.
_COLLECTIVE_AXIS_POS: Dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "ppermute": 1, "all_to_all": 1, "psum_scatter": 1, "pshuffle": 1,
    "pswapaxes": 1, "axis_index": 0,
}

#: Serialization sinks: module-rooted call terminals, by root name.
_SINK_FUNCS: Dict[str, Tuple[str, ...]] = {
    "json": ("dumps", "dump"),
    "pickle": ("dumps", "dump"),
    "struct": ("pack", "pack_into"),
    "base64": ("b64encode", "b85encode", "standard_b64encode",
               "urlsafe_b64encode"),
}
_SINK_BARE = frozenset({"b64encode", "b85encode"})

#: `donatemon.instrument(jit(...), ...)` wraps a donating callable
#: without changing its donation contract — treat it as transparent.
_TRANSPARENT_WRAPPERS = frozenset({"instrument"})


def _donated_positions(call: ast.Call) -> Tuple[int, ...]:
    """donate_argnums=(0, 1) positions of a jit(...) call node."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        nodes = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        out = [n.value for n in nodes
               if isinstance(n, ast.Constant) and isinstance(n.value, int)]
        return tuple(sorted(set(out)))
    return ()


def _pytree_sig(node: ast.AST):
    """Literal container structure of a call argument, or None when the
    treedef is not statically visible. ('dict', keys-in-order) keeps the
    ORDER — jax treedefs are insertion-order-sensitive for dicts only up
    to sorting, but a reordered literal is the reviewable smell."""
    if isinstance(node, ast.Dict):
        keys = [k.value for k in node.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)]
        if len(keys) == len(node.keys) and keys:
            return ("dict", tuple(keys))
        return None
    if isinstance(node, ast.List):
        return ("list", len(node.elts))
    if isinstance(node, ast.Tuple):
        return ("tuple", len(node.elts))
    return None


def _sigs_conflict(a, b) -> Optional[str]:
    """The churn description when two literal sigs imply the same
    leaves under different treedefs, else None."""
    if a == b or a is None or b is None:
        return None
    if a[0] == "dict" and b[0] == "dict" and set(a[1]) == set(b[1]):
        return ("same dict keys in a different order "
                f"({', '.join(a[1])} vs {', '.join(b[1])})")
    if {a[0], b[0]} == {"list", "tuple"} and a[1] == b[1]:
        return f"list-vs-tuple of the same length ({a[1]})"
    return None


@dataclass
class _Donation:
    """Why an identity is dead: the donating call."""
    site: Tuple[str, int]          # (path, line) of the donating call
    callee: str                    # rendered callee, e.g. "self._step"
    pos: int                       # donated argument position


@dataclass
class _Placement:
    spec: str                      # normalized spec text
    site: Tuple[str, int]          # (path, line)
    via: str                       # "with_sharding_constraint"/"device_put"


@dataclass
class _ModCtx:
    """Per-module helpers shared by both walker passes."""
    mod: ModuleInfo
    fl: _FileLinter                # engine adapter: imports + _devicey
    traced_names: Set[str] = field(default_factory=set)


@dataclass
class _CallSig:
    """A GL803 observation: one call site's literal arg structures."""
    key: str                       # callee identity
    sigs: Tuple                    # per-arg _pytree_sig results
    mod: ModuleInfo
    node: ast.Call


class _ShardAnalysis:
    def __init__(self, prog: Program, *,
                 hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES):
        self.prog = prog
        self.graph = CallGraph(prog)
        self.hot_prefixes = hot_prefixes
        self.findings: List[Finding] = []
        self._allow: Dict[str, Dict[int, Set[str]]] = {}
        self._emitted: Set[Tuple] = set()
        # donation facts --------------------------------------------------
        #: callee key -> {donated position: (path, line)}. Keys are
        #: function qualnames, "Cls.qualname.attr" for self-attr
        #: bindings, and "mod.name.var" for module-global bindings.
        self.donates: Dict[str, Dict[int, Tuple[str, int]]] = {}
        #: qualname -> donated positions of the callable it RETURNS
        self.returns_donating: Dict[str, Tuple[int, ...]] = {}
        #: jitted callee keys (donating or not) for GL803
        self.jitted: Set[str] = set()
        #: qualname -> {param index: (sink description, (path, line))}
        self.ser_flow: Dict[str, Dict[int, Tuple[str, Tuple[str, int]]]] = {}
        # pre-scan products ----------------------------------------------
        self._mods: Dict[str, _ModCtx] = {}
        self._sigs: List[_CallSig] = []

    # ------------------------------------------------------------ entry
    def run(self) -> List[Finding]:
        for mod in self.prog.modules.values():
            self._mods[mod.name] = self._mod_ctx(mod)
        self._collect_direct_facts()
        self._fixpoint_summaries()
        for fn in self.prog.functions.values():
            _FnFlow(self, fn).run()
        self._gl803()
        self.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return self.findings

    def _mod_ctx(self, mod: ModuleInfo) -> _ModCtx:
        fl = _FileLinter(mod.path, mod.source, hot=True)
        fl.imports = _Imports(mod.tree)
        fl.module_defs = {}
        mc = _ModCtx(mod, fl)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                slots = fl.imports.wrapper_slots(node.func)
                if slots is None:
                    continue
                for i in slots:
                    if i < len(node.args) and isinstance(node.args[i],
                                                         ast.Name):
                        mc.traced_names.add(node.args[i].id)
        return mc

    # ------------------------------------------------------------- emit
    def _emit(self, rule: str, mod: ModuleInfo, node: ast.AST,
              message: str,
              related: Sequence[Tuple[str, int, str]] = (),
              dedup: Optional[Tuple] = None) -> None:
        line = getattr(node, "lineno", 1)
        if dedup is None:
            dedup = (rule, mod.path, line, message)
        if dedup in self._emitted:
            return
        self._emitted.add(dedup)
        end = getattr(node, "end_lineno", line) or line
        allow = self._allow.setdefault(
            mod.path, _collect_suppressions(mod.lines))
        if suppression_covers(mod.lines, allow, rule, line, end):
            return
        snippet = (mod.lines[line - 1].strip()
                   if 0 < line <= len(mod.lines) else "")
        self.findings.append(Finding(
            rule, mod.path, line, getattr(node, "col_offset", 0),
            message, snippet, related=tuple(related)))

    # ------------------------------------------------ direct fact scan
    def _collect_direct_facts(self) -> None:
        """Decorator donations, jit bindings (module/attr), and jitted
        callee keys — everything visible without a fixpoint."""
        for fn in self.prog.functions.values():
            mc = self._mods[fn.module.name]
            dec_call = self._jit_decorator_call(fn, mc)
            if dec_call is not None:
                self.jitted.add(fn.qualname)
                pos = _donated_positions(dec_call) \
                    if isinstance(dec_call, ast.Call) else ()
                if pos:
                    self.donates[fn.qualname] = {
                        p: (fn.module.path, fn.node.lineno) for p in pos}
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign):
                    self._scan_binding_assign(fn, mc, node)
        for mod in self.prog.modules.values():
            mc = self._mods[mod.name]
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign):
                    self._scan_module_binding(mod, mc, stmt)

    def _jit_decorator_call(self, fn: FunctionInfo,
                            mc: _ModCtx) -> Optional[ast.AST]:
        """The jit-family decorator node of `fn`, preferring the call
        form (which carries donate_argnums), else None."""
        fl = mc.fl
        for dec in fn.node.decorator_list:
            jf = fl._jitish_decorator(dec)
            if jf is None or _terminal(jf) not in ("jit", "pjit", "pmap"):
                continue
            if isinstance(dec, ast.Call):
                return dec            # @partial(jax.jit, ...) / @jit(...)
            return jf
        return None

    def _donating_value(self, mc: _ModCtx,
                        value: ast.AST) -> Optional[Tuple[Tuple[int, ...],
                                                          bool]]:
        """(donated positions, is_jitted) when `value` is a jit-family
        call (possibly wrapped in donatemon.instrument), else None."""
        if (isinstance(value, ast.Call)
                and _terminal(value.func) in _TRANSPARENT_WRAPPERS
                and value.args):
            return self._donating_value(mc, value.args[0])
        if isinstance(value, ast.Call) \
                and mc.fl.imports.is_jit_family(value.func):
            return _donated_positions(value), True
        return None

    def _scan_binding_assign(self, fn: FunctionInfo, mc: _ModCtx,
                             node: ast.Assign) -> None:
        """`self.attr = jax.jit(f, donate_argnums=...)` anywhere in a
        method body types the class attribute as a donating callable
        (the lazily-built-step idiom); the indirect form
        `self.attr = self._build_step()` is resolved by the fixpoint."""
        got = self._donating_value(mc, node.value)
        if got is None:
            return
        pos, _ = got
        site = (fn.module.path, node.lineno)
        for t in node.targets:
            if (fn.cls is not None and isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == fn.self_name):
                key = f"{fn.cls.qualname}.{t.attr}"
                self.jitted.add(key)
                if pos:
                    self.donates[key] = {p: site for p in pos}

    def _scan_module_binding(self, mod: ModuleInfo, mc: _ModCtx,
                             stmt: ast.Assign) -> None:
        got = self._donating_value(mc, stmt.value)
        if got is None:
            return
        pos, _ = got
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                key = f"{mod.name}.{t.id}"
                self.jitted.add(key)
                if pos:
                    self.donates[key] = {
                        p: (mod.path, stmt.lineno) for p in pos}

    # ------------------------------------------------------- fixpoints
    def _fixpoint_summaries(self) -> None:
        """Three bounded fixpoints over the call graph:
        1. returns_donating — `return jax.jit(f, donate_argnums=...)`
           (or a local bound to one, or a call to a fn that returns
           one) makes the *caller's binding* a donating callable;
        2. donates — a fn that forwards param i into a donated slot of
           a resolved donating call donates position i itself;
        3. ser_flow — a fn whose param i reaches a serialization sink
           unlaundered taints its callers' argument i."""
        summaries = {fn.qualname: _FnSummary(self, fn).collect()
                     for fn in self.prog.functions.values()}
        for _ in range(MAX_PROPAGATION_ROUNDS):
            changed = False
            for q, s in summaries.items():
                changed |= self._apply_summary(q, s)
            if not changed:
                break

    def _apply_summary(self, q: str, s: "_FnSummaryData") -> bool:
        changed = False
        # 1. returns_donating / attr-from-returner bindings. Only a
        # *returner* chain propagates (`return self._build_step()`) —
        # calling a donating callable returns arrays, not a callable.
        for ret_keys in s.return_calls:
            for key, _offset in ret_keys:
                pos = self.returns_donating.get(key)
                if pos and self.returns_donating.get(q) != pos:
                    self.returns_donating[q] = pos
                    changed = True
        for (bind_key, callee_keys, site) in s.bindings_from_calls:
            for key, offset in callee_keys:
                pos = self.returns_donating.get(key)
                if pos:
                    cur = self.donates.setdefault(bind_key, {})
                    self.jitted.add(bind_key)
                    for p in pos:
                        if p not in cur:
                            cur[p] = site
                            changed = True
        # 2. donation through helpers; 3. serialization through helpers
        for (callee_keys, arg_params, node_site) in s.calls:
            for key, offset in callee_keys:
                dpos = self.donates.get(key, {})
                for p, dsite in dpos.items():
                    ai = p - offset
                    param = arg_params.get(ai)
                    if param is None:
                        continue
                    cur = self.donates.setdefault(q, {})
                    if param not in cur:
                        cur[param] = node_site
                        changed = True
                spos = self.ser_flow.get(key, {})
                for p, (what, ssite) in spos.items():
                    ai = p - offset
                    param = arg_params.get(ai)
                    if param is None:
                        continue
                    cur2 = self.ser_flow.setdefault(q, {})
                    if param not in cur2:
                        cur2[param] = (what, ssite)
                        changed = True
        for (pidx, what, site) in s.direct_sinks:
            cur2 = self.ser_flow.setdefault(q, {})
            if pidx not in cur2:
                cur2[pidx] = (what, site)
                changed = True
        return changed

    # ------------------------------------------------- call resolution
    def callee_keys(self, fn: FunctionInfo,
                    call: ast.Call) -> List[Tuple[str, int]]:
        """(key, arg-offset) pairs a call site may dispatch to. Offset
        is 1 for bound-method calls resolved to a def whose first param
        is self (donate_argnums counts params, calls pass args)."""
        out: List[Tuple[str, int]] = []
        func = call.func
        # self.attr(...) — a jit-binding class attribute
        if (fn.cls is not None and isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == fn.self_name):
            out.append((f"{fn.cls.qualname}.{func.attr}", 0))
        # module-global binding / local binding keys are added by the
        # walker (it owns the local scope); resolved defs:
        for cand in self.graph.resolve(fn, call):
            offset = 0
            if cand.cls is not None and isinstance(func, ast.Attribute):
                offset = 1        # self.m(a): a is param 1
            out.append((cand.qualname, offset))
        if isinstance(func, ast.Name):
            out.append((f"{fn.module.name}.{func.id}", 0))
        return out

    # ------------------------------------------------------------ GL803
    def note_call_sig(self, key: str, mod: ModuleInfo,
                      node: ast.Call) -> None:
        sigs = tuple(_pytree_sig(a) for a in node.args)
        if any(s is not None for s in sigs):
            self._sigs.append(_CallSig(key, sigs, mod, node))

    def _gl803(self) -> None:
        by_key: Dict[str, List[_CallSig]] = {}
        for cs in self._sigs:
            if cs.key in self.jitted:
                by_key.setdefault(cs.key, []).append(cs)
        for key, sites in by_key.items():
            sites.sort(key=lambda c: (c.mod.path, c.node.lineno))
            for i, a in enumerate(sites):
                for b in sites[i + 1:]:
                    n = min(len(a.sigs), len(b.sigs))
                    for ai in range(n):
                        why = _sigs_conflict(a.sigs[ai], b.sigs[ai])
                        if why is None:
                            continue
                        short = key.split(".")[-1]
                        self._emit(
                            "GL803", b.mod, b.node,
                            f"jitted callee `{short}` is called with a "
                            f"different pytree structure for argument "
                            f"{ai} than at {a.mod.path}:"
                            f"{a.node.lineno} — {why}; same leaves, "
                            f"different treedef, so the jit cache "
                            f"recompiles silently",
                            related=[(a.mod.path, a.node.lineno,
                                      "first structure used here")],
                            dedup=("GL803", key, ai))
                        break


@dataclass
class _FnSummaryData:
    #: resolved (key, offset) lists of calls in `return <call>` position
    return_calls: List[List[Tuple[str, int]]] = field(default_factory=list)
    #: (binding key, callee keys, site) for `self.attr = self._build()`
    bindings_from_calls: List[Tuple[str, List[Tuple[str, int]],
                                    Tuple[str, int]]] = \
        field(default_factory=list)
    #: (callee keys, {arg idx: caller param idx}, (path, line))
    calls: List[Tuple[List[Tuple[str, int]], Dict[int, int],
                      Tuple[str, int]]] = field(default_factory=list)
    #: (param idx, sink description, (path, line)) — direct sinks
    direct_sinks: List[Tuple[int, str, Tuple[str, int]]] = \
        field(default_factory=list)


class _FnSummary:
    """Unordered single sweep over one function body collecting the
    facts the fixpoint needs (no emission, no dead-tracking)."""

    def __init__(self, an: _ShardAnalysis, fn: FunctionInfo):
        self.an = an
        self.fn = fn
        self.mc = an._mods[fn.module.name]
        params = [a.arg for a in
                  getattr(fn.node.args, "posonlyargs", [])
                  + fn.node.args.args]
        self.param_idx = {p: i for i, p in enumerate(params)}
        self.data = _FnSummaryData()

    def collect(self) -> _FnSummaryData:
        fn, d = self.fn, self.data
        path = fn.module.path
        # pass 1: local names bound to donating callables (needed so a
        # bare `return fn` after `fn = jax.jit(...)` summarizes)
        local_don: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                got = self.an._donating_value(self.mc, node.value)
                if got is not None and got[0]:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_don[t.id] = got[0]
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                if isinstance(node.value, ast.Call):
                    got = self.an._donating_value(self.mc, node.value)
                    if got is not None and got[0]:
                        self.an.returns_donating.setdefault(
                            fn.qualname, got[0])
                    else:
                        d.return_calls.append(
                            self.an.callee_keys(fn, node.value))
                elif isinstance(node.value, ast.Name) \
                        and node.value.id in local_don:
                    self.an.returns_donating.setdefault(
                        fn.qualname, local_don[node.value.id])
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                keys = self.an.callee_keys(fn, node.value)
                site = (path, node.lineno)
                for t in node.targets:
                    if (fn.cls is not None
                            and isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == fn.self_name):
                        d.bindings_from_calls.append(
                            (f"{fn.cls.qualname}.{t.attr}", keys, site))
            if isinstance(node, ast.Call):
                self._scan_call(node)
        return d

    def _scan_call(self, node: ast.Call) -> None:
        fn, d = self.fn, self.data
        path = fn.module.path
        keys = self.an.callee_keys(fn, node)
        if keys:
            arg_params = {
                i: self.param_idx[a.id]
                for i, a in enumerate(node.args)
                if isinstance(a, ast.Name) and a.id in self.param_idx}
            # self.attr params: `self.params` forwarded — identity is
            # not a param index, so only bare names summarize (sound:
            # missing a flow only loses a finding, never invents one)
            if arg_params:
                d.calls.append((keys, arg_params, (path, node.lineno)))
        sink = _sink_of(node)
        if sink is None:
            return
        what, payload = sink
        for a in payload:
            if isinstance(a, ast.Name) and a.id in self.param_idx:
                d.direct_sinks.append(
                    (self.param_idx[a.id], what, (path, node.lineno)))
            elif (isinstance(a, ast.Attribute)
                  and isinstance(a.value, ast.Name)
                  and a.value.id in self.param_idx
                  and a.attr not in ("shape", "ndim", "dtype", "size")):
                d.direct_sinks.append(
                    (self.param_idx[a.value.id], what,
                     (path, node.lineno)))


def _sink_of(node: ast.Call) -> Optional[Tuple[str, List[ast.AST]]]:
    """(sink description, payload expressions) for serialization sinks,
    else None. `.tobytes()` reports its receiver as the payload."""
    func = node.func
    term = _terminal(func)
    if term == "tobytes" and isinstance(func, ast.Attribute) \
            and not node.args:
        return (".tobytes()", [func.value])
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        root = func.value.id
        if term in _SINK_FUNCS.get(root, ()):
            return (f"{root}.{term}()", list(node.args))
    if isinstance(func, ast.Name) and term in _SINK_BARE:
        return (f"{term}()", list(node.args))
    return None


class _FnFlow:
    """Ordered statement walk of one function body: tracks dead
    (donated) identities, placement tags, and device taint; emits
    GL801/GL802/GL804/GL805 and records GL803 call signatures.

    Identities are bare names ("x") and one-level self attributes
    ("self.params"). Branch arms fork the dead-set and merge may-dead;
    loop bodies run twice to expose loop-carried donation."""

    def __init__(self, an: _ShardAnalysis, fn: FunctionInfo):
        self.an = an
        self.fn = fn
        self.mc = an._mods[fn.module.name]
        self.fl = self.mc.fl
        self.dead: Dict[str, _Donation] = {}
        self.placed: Dict[str, _Placement] = {}
        #: local names bound to donating/jitted callables
        self.local_don: Dict[str, Dict[int, Tuple[str, int]]] = {}
        self.local_jit: Set[str] = set()
        self.ctx = _Ctx()          # .dev drives the engine's _devicey
        self.traced = self._is_traced()

    def _is_traced(self) -> bool:
        if self.fn.name in self.mc.traced_names:
            return True
        return self.an._jit_decorator_call(self.fn, self.mc) is not None

    # ---------------------------------------------------------- helpers
    def _ident(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == self.fn.self_name):
            return f"{node.value.id}.{node.attr}"
        return None

    def _devicey(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Dict):    # engine stops at dict literals
            return any(self._devicey(v) for v in node.values
                       if v is not None) \
                or any(self._devicey(k) for k in node.keys
                       if k is not None)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._devicey(e) for e in node.elts)
        return self.fl._devicey(node, self.ctx)

    def run(self) -> None:
        for stmt in self.fn.node.body:
            self._stmt(stmt)

    # ------------------------------------------------------- statements
    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return      # nested defs run later; fresh scope, no flow
        if isinstance(node, ast.Assign):
            self._assign(node.targets, node.value, node)
        elif isinstance(node, ast.AugAssign):
            self._expr(node.value)
            self._expr(node.target)
            ident = self._ident(node.target)
            if ident is not None:
                self.dead.pop(ident, None)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._assign([node.target], node.value, node)
        elif isinstance(node, ast.If):
            self._expr(node.test)
            before = dict(self.dead)
            self._body(node.body)
            after_body = self.dead
            self.dead = dict(before)
            self._body(node.orelse)
            self.dead.update(after_body)       # may-dead merge
        elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(node, ast.While):
                self._expr(node.test)
            else:
                self._expr(node.iter)
                t_ident = self._ident(node.target)
                if t_ident is not None:
                    self.dead.pop(t_ident, None)
            for _round in (0, 1):              # expose loop-carried UAD
                self._body(node.body)
            self._body(node.orelse)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._expr(item.context_expr)
            self._body(node.body)
        elif isinstance(node, ast.Try):
            self._body(node.body)
            for h in node.handlers:
                if h.type is not None:
                    self._expr(h.type)
                self._body(h.body)
            self._body(node.orelse)
            self._body(node.finalbody)
        elif isinstance(node, (ast.Return, ast.Expr)):
            if node.value is not None:
                self._expr(node.value)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self._expr(node.exc)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                ident = self._ident(t)
                if ident is not None:
                    self.dead.pop(ident, None)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self._stmt(child)
                elif isinstance(child, ast.expr):
                    self._expr(child)

    def _body(self, stmts: List[ast.stmt]) -> None:
        for s in stmts:
            self._stmt(s)

    def _assign(self, targets: List[ast.AST], value: ast.AST,
                stmt: ast.AST) -> None:
        self._expr(value)                     # reads + donation marking
        # local jit/donating binding?
        got = self.an._donating_value(self.mc, value)
        bound_don: Optional[Dict[int, Tuple[str, int]]] = None
        bound_jit = got is not None
        if got is not None and got[0]:
            bound_don = {p: (self.fn.module.path, stmt.lineno)
                         for p in got[0]}
        if bound_don is None and isinstance(value, ast.Call):
            # `fn = self._build_step()` — returner fixpoint result
            for key, _off in self.an.callee_keys(self.fn, value):
                pos = self.an.returns_donating.get(key)
                if pos:
                    bound_don = {p: (self.fn.module.path, stmt.lineno)
                                 for p in pos}
                    bound_jit = True
                    break
        placement = self._placement_of(value)
        devicey = not self.traced and self._devicey(value)
        if not devicey and not self.traced and isinstance(value, ast.Call):
            # the engine's name-based taint misses jit results bound
            # under neutral names — but THIS pass knows which callees
            # are jitted, so `y = fwd(x)` taints when fwd is jit-bound
            vf = value.func
            if isinstance(vf, ast.Name) and vf.id in self.local_jit:
                devicey = True
            elif self.an._donating_value(self.mc, vf) is not None:
                devicey = True        # jax.jit(...)(...) called inline
            elif any(key in self.an.jitted
                     for key, _ in self.an.callee_keys(self.fn, value)):
                devicey = True
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
                continue
            if isinstance(t, ast.Starred):
                stack.append(t.value)
                continue
            ident = self._ident(t)
            if ident is None:
                continue
            self.dead.pop(ident, None)        # reassignment revives
            if isinstance(t, ast.Name):
                if bound_don is not None:
                    self.local_don[t.id] = bound_don
                if bound_jit:
                    self.local_jit.add(t.id)
                    self.an.jitted.add(
                        f"{self.fn.qualname}.{t.id}")
                (self.ctx.dev.add if devicey
                 else self.ctx.dev.discard)(t.id)
            if placement is not None:
                self.placed[ident] = placement
            elif self._ident(value) in self.placed:
                self.placed[ident] = self.placed[self._ident(value)]
            else:
                self.placed.pop(ident, None)

    # ------------------------------------------------------ expressions
    def _expr(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return
        ident = self._ident(node)
        if ident is not None:
            self._check_dead(node, ident)
            if isinstance(node, ast.Attribute):
                return                         # don't re-check the base
        if isinstance(node, ast.BinOp):
            self._check_combine(node, [node.left, node.right], "binop")
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp,
                             ast.DictComp)):
            # comprehension generators are ast.comprehension, not
            # ast.expr — walk their iter/ifs explicitly or reads like
            # `for a in state.values()` are invisible to the dead check
            for comp in node.generators:
                self._expr(comp.iter)
                for cond in comp.ifs:
                    self._expr(cond)
            if isinstance(node, ast.DictComp):
                self._expr(node.key)
                self._expr(node.value)
            else:
                self._expr(node.elt)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _check_dead(self, node: ast.AST, ident: str) -> None:
        don = self.dead.get(ident)
        if don is None:
            return
        self.an._emit(
            "GL801", self.fn.module, node,
            f"`{ident}` is read after being donated to "
            f"`{don.callee}` (donate_argnums position {don.pos}) — the "
            f"buffer is dead by contract; rebind the result in the "
            f"same statement (`x, ... = {don.callee}(x, ...)`) or drop "
            f"the donation",
            related=[(don.site[0], don.site[1],
                      f"donated here, argument {don.pos} of "
                      f"`{don.callee}`")],
            dedup=("GL801", self.fn.qualname, id(node), ident))
        # one report per (site, identity); keep walking without cascades
        self.dead.pop(ident, None)

    def _placement_of(self, node: ast.AST) -> Optional[_Placement]:
        """Tag for `with_sharding_constraint(x, SPEC)`/`device_put(x,
        SPEC)` values; propagates through a directly-placed name."""
        if isinstance(node, ast.Call):
            term = _terminal(node.func)
            if term in _PLACEMENT_FUNCS and len(node.args) >= 2:
                try:
                    spec = ast.unparse(node.args[1])
                except Exception:       # pragma: no cover - unparse total
                    spec = "<spec>"
                spec = "".join(spec.split())
                return _Placement(spec,
                                  (self.fn.module.path, node.lineno),
                                  term or "")
            return None
        ident = self._ident(node)
        if ident is not None:
            return self.placed.get(ident)
        return None

    def _check_combine(self, node: ast.AST, operands: List[ast.AST],
                       how: str) -> None:
        tags: List[Tuple[ast.AST, _Placement]] = []
        for op in operands:
            p = self._placement_of(op)
            if p is not None:
                tags.append((op, p))
        for i in range(len(tags)):
            for j in range(i + 1, len(tags)):
                a, b = tags[i][1], tags[j][1]
                if a.spec != b.spec:
                    self.an._emit(
                        "GL802", self.fn.module, node,
                        f"{how} combines values under different "
                        f"placement specs ({a.spec} via {a.via} vs "
                        f"{b.spec} via {b.via}) — GSPMD inserts an "
                        f"implicit resharding collective here; "
                        f"constrain both operands to one spec first",
                        related=[(a.site[0], a.site[1],
                                  f"placed as {a.spec} here"),
                                 (b.site[0], b.site[1],
                                  f"placed as {b.spec} here")],
                        dedup=("GL802", self.fn.qualname, id(node)))
                    return

    # ------------------------------------------------------------ calls
    def _call(self, node: ast.Call) -> None:
        func = node.func
        term = _terminal(func)

        # visit callee receiver + args FIRST: the call reads its
        # arguments while they are still alive; donation kills after.
        if isinstance(func, ast.Attribute):
            self._expr(func.value)
        elif isinstance(func, (ast.Call, ast.Lambda)):
            self._expr(func)
        for a in node.args:
            self._expr(a)
        for k in node.keywords:
            self._expr(k.value)

        # GL805 — collective with a literal axis name
        self._check_collective(node, term)

        # GL802 — combining callables (concatenate/stack/...)
        if term in _COMBINE_FUNCS:
            ops: List[ast.AST] = []
            for a in node.args:
                if isinstance(a, (ast.Tuple, ast.List)):
                    ops.extend(a.elts)
                else:
                    ops.append(a)
            self._check_combine(node, ops, f"{term}()")

        # GL804 — direct serialization sink
        sink = _sink_of(node)
        if sink is not None:
            what, payload = sink
            for a in payload:
                if self._devicey(a):
                    self.an._emit(
                        "GL804", self.fn.module, node,
                        f"device-tainted value reaches {what} without "
                        f"an np.asarray()/jax.device_get() laundering "
                        f"point — the wire format captures a live "
                        f"device buffer; copy to host first",
                        dedup=("GL804", self.fn.qualname, id(node)))
                    break

        # donation + helper-mediated serialization at resolved calls
        keys = self.an.callee_keys(self.fn, node)
        if isinstance(func, ast.Name) and func.id in self.local_don:
            self._donate_args(node, self.local_don[func.id], 0,
                              func.id)
        if isinstance(func, ast.Name) and func.id in self.local_jit:
            self.an.note_call_sig(
                f"{self.fn.qualname}.{func.id}", self.fn.module, node)
        # immediately-invoked donating jit: jax.jit(f, donate...)(x)
        if isinstance(func, ast.Call):
            inner = self.an._donating_value(self.mc, func)
            if inner is not None and inner[0]:
                site = (self.fn.module.path, node.lineno)
                self._donate_args(
                    node, {p: site for p in inner[0]}, 0,
                    _terminal(func.args[0].func
                              if isinstance(func.args[0], ast.Call)
                              else func.args[0])
                    if func.args else "jit(...)")
        for key, offset in keys:
            dpos = self.an.donates.get(key)
            if dpos:
                callee_desc = self._render_callee(func, key)
                self._donate_args(node, dpos, offset, callee_desc)
            if key in self.an.jitted:
                self.an.note_call_sig(key, self.fn.module, node)
            spos = self.an.ser_flow.get(key)
            if spos:
                for p, (what, ssite) in spos.items():
                    ai = p - offset
                    if 0 <= ai < len(node.args) \
                            and self._devicey(node.args[ai]):
                        self.an._emit(
                            "GL804", self.fn.module, node,
                            f"device-tainted argument {ai} flows "
                            f"through `{self._render_callee(func, key)}"
                            f"` into {what} with no laundering point "
                            f"on the way — copy to host "
                            f"(np.asarray/jax.device_get) before the "
                            f"call",
                            related=[(ssite[0], ssite[1],
                                      f"serialized here via {what}")],
                            dedup=("GL804", self.fn.qualname, id(node),
                                   ai))

    def _render_callee(self, func: ast.AST, key: str) -> str:
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            return f"{func.value.id}.{func.attr}"
        if isinstance(func, ast.Name):
            return func.id
        return key.split(".")[-1]

    def _donate_args(self, node: ast.Call,
                     dpos: Dict[int, Tuple[str, int]], offset: int,
                     callee_desc: str) -> None:
        site = (self.fn.module.path, node.lineno)
        for p in dpos:
            ai = p - offset
            if not (0 <= ai < len(node.args)):
                continue
            ident = self._ident(node.args[ai])
            if ident is None:
                continue
            self.dead[ident] = _Donation(site, callee_desc, p)

    def _check_collective(self, node: ast.Call,
                          term: Optional[str]) -> None:
        if term not in _COLLECTIVE_AXIS_POS:
            return
        imports = self.fl.imports
        func = node.func
        rooted = imports.is_jax_call_root(func) or (
            isinstance(func, ast.Name) and func.id in imports.from_jax)
        if not rooted:
            return
        norm = self.fn.module.path.replace(os.sep, "/")
        if norm.endswith("parallel/mesh.py"):
            return
        cands: List[ast.AST] = []
        pos = _COLLECTIVE_AXIS_POS[term]
        if pos < len(node.args):
            cands.append(node.args[pos])
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                cands.append(kw.value)
        for c in cands:
            if isinstance(c, ast.Constant) and isinstance(c.value, str):
                self.an._emit(
                    "GL805", self.fn.module, node,
                    f"{term}() axis name {c.value!r} is a string "
                    f"literal outside parallel/mesh.py — read mesh "
                    f"axis names from the active MeshContext / "
                    f"parallel.mesh constants so a mesh reshape "
                    f"cannot silently detach this collective",
                    dedup=("GL805", self.fn.qualname, id(node)))
                return


# ------------------------------------------------------------ public API

def analyze_shardflow_program(
        prog: Program, *,
        hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
) -> List[Finding]:
    """Run the GL8xx sharding/donation pass over a prebuilt Program —
    the shared-callgraph entry point lint_paths uses so the lockset and
    shardflow passes parse the repo once."""
    return _ShardAnalysis(prog, hot_prefixes=hot_prefixes).run()


def analyze_shardflow_sources(
        sources: Sequence[Tuple[str, str]], *,
        hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
) -> List[Finding]:
    return analyze_shardflow_program(Program.from_sources(sources),
                                     hot_prefixes=hot_prefixes)


def analyze_shardflow_paths(
        files: Sequence[str], *,
        hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
) -> List[Finding]:
    return analyze_shardflow_program(Program.from_paths(files),
                                     hot_prefixes=hot_prefixes)
