"""graft-lint rule registry.

Every rule the engine can emit, with the metadata the reporters and the
runtime cross-check need: stable id, category, severity, one-line
summary. The ids are contractual — they appear in suppression comments
(`# graft: allow(GL202): reason`), in `.graftlint-baseline.json`, in
SARIF output, and in the hints the runtime RecompileWatchdog /
HostSyncMonitor attach to their events — so ids are append-only; never
renumber.

Categories map onto the failure modes this codebase actually has
(PERF_NOTES contracts):

  tracer    — concretizing a tracer inside a traced function
              (TracerBoolConversionError / silent constant-folding)
  recompile — patterns that defeat the jit cache (the
              RecompileWatchdog's static counterpart)
  sync      — un-suppressed device→host syncs in modules declared hot
              (the HostSyncMonitor's static counterpart); suppressible
              with `# graft: allow-sync(reason)`
  lock      — mutation of lock-guarded shared state outside its lock
  hygiene   — general patterns that mask errors in worker threads
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

ERROR = "error"
WARNING = "warning"

#: Bumped whenever rule semantics change in a way that invalidates
#: previously-computed findings; the `.graftlint-cache.json` result
#: cache (analysis/cache.py) keys on it, so a rules change forces a
#: cold re-lint even when no source file changed.
RULES_VERSION = 2

CAT_TRACER = "tracer"
CAT_RECOMPILE = "recompile"
CAT_SYNC = "sync"
CAT_LOCK = "lock"
CAT_HYGIENE = "hygiene"
CAT_SHARDING = "sharding"
CAT_OBSERVE = "observe"


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    category: str
    severity: str
    summary: str


_ALL = (
    Rule("GL000", "parse-failure", CAT_HYGIENE, ERROR,
         "file does not parse — nothing else can be checked"),
    # ------------------------------------------------------ tracer-safety
    Rule("GL001", "tracer-implicit-cast", CAT_TRACER, ERROR,
         "bool()/int()/float() on a tracer-derived value inside a traced "
         "function — raises TracerBoolConversionError or bakes a "
         "trace-time constant into the program"),
    Rule("GL002", "tracer-concretize", CAT_TRACER, ERROR,
         ".item()/.tolist()/np.asarray()/jax.device_get()/"
         ".block_until_ready() on a tracer-derived value inside a traced "
         "function — tracers have no buffer to materialize"),
    Rule("GL003", "tracer-python-branch", CAT_TRACER, ERROR,
         "Python if/while on a tracer-derived value inside a traced "
         "function — use lax.cond/lax.while_loop/jnp.where"),
    Rule("GL004", "tracer-assert", CAT_TRACER, ERROR,
         "assert on a tracer-derived value inside a traced function — "
         "use checkify or move the check to host code"),
    Rule("GL005", "tracer-python-loop", CAT_TRACER, ERROR,
         "Python for-loop over a tracer-derived value (or range() of "
         "one) inside a traced function — use lax.scan/lax.fori_loop"),
    # --------------------------------------------------- recompile hazards
    Rule("GL101", "unhashable-static-arg", CAT_RECOMPILE, ERROR,
         "jit static argument whose parameter default is a mutable "
         "(unhashable) container — every call either crashes on hashing "
         "or defeats the jit cache key"),
    Rule("GL102", "jit-of-fresh-function", CAT_RECOMPILE, ERROR,
         "jit/pmap applied to a function object created per call "
         "(immediately-invoked jit, or a jit-decorated def nested in a "
         "function) — the cache keys on function identity, so every "
         "call recompiles"),
    Rule("GL103", "jit-in-loop", CAT_RECOMPILE, ERROR,
         "jit/pmap wrapping (or decorating) a function inside a loop "
         "body — a fresh compiled program per iteration"),
    # -------------------------------------------------------- sync hygiene
    Rule("GL201", "hot-sync-materialize", CAT_SYNC, ERROR,
         "device→host materialization (.item()/.tolist()/np.asarray()/"
         "jax.device_get()) on a device value in a hot module without "
         "`# graft: allow-sync(reason)`"),
    Rule("GL202", "hot-implicit-sync", CAT_SYNC, ERROR,
         "implicit device→host sync (bool()/int()/float() or Python "
         "truthiness on a device value) in a hot module without "
         "`# graft: allow-sync(reason)`"),
    Rule("GL203", "hot-block-until-ready", CAT_SYNC, ERROR,
         ".block_until_ready() in a hot module without "
         "`# graft: allow-sync(reason)` — serializes the dispatch "
         "pipeline"),
    Rule("GL204", "device-array-leak", CAT_SYNC, WARNING,
         "device value passed to logging/print/json serialization in a "
         "hot module — forces a sync and can pin device buffers in "
         "log records"),
    # ----------------------------------------------------- lock discipline
    Rule("GL301", "unlocked-shared-mutation", CAT_LOCK, ERROR,
         "mutation of an attribute of a lock-owning object outside a "
         "`with <lock>:` block — racy against the locked readers"),
    # ---------------------------------------------------- general hygiene
    Rule("GL401", "mutable-default-arg", CAT_HYGIENE, WARNING,
         "mutable default argument (list/dict/set) — shared across "
         "calls and across AsyncDataSetIterator-style worker threads"),
    Rule("GL402", "bare-except", CAT_HYGIENE, WARNING,
         "bare `except:` — catches KeyboardInterrupt/SystemExit and "
         "masks worker-thread errors; catch Exception (or narrower)"),
    Rule("GL403", "silent-exception-swallow", CAT_HYGIENE, WARNING,
         "`except ...: pass` — the error disappears; log it, re-raise, "
         "or narrow the handler"),
    # ------------------------------------------------- sharding discipline
    Rule("GL501", "mesh-outside-spine", CAT_SHARDING, WARNING,
         "direct jax.sharding.Mesh(...) / jax.devices() construction "
         "outside parallel/mesh.py — placement decided off-spine drifts "
         "from the MeshContext the executor threads through training; "
         "build meshes via parallel.mesh.make_mesh()/MeshContext and read "
         "device topology via parallel.mesh.device_count()"),
    # ------------------------------------------------ observability safety
    Rule("GL601", "span-attr-device-taint", CAT_OBSERVE, WARNING,
         "tracer- or device-derived value passed as a span/exemplar "
         "attribute (span(...)/record_span(...)/observe(exemplar=...)) — "
         "inside a traced function it concretizes the tracer; in a hot "
         "module it forces a device→host sync on the telemetry path, "
         "breaking the sync-free span contract; pass host scalars only"),
    Rule("GL602", "snapshot-in-hot-loop", CAT_OBSERVE, WARNING,
         "full MetricsRegistry/series snapshot (snapshot()/"
         "to_prometheus()/to_jsonl()) inside a traced function or a "
         "hot-module loop — rendering every series sorts histogram "
         "reservoirs and is O(all metrics) reader work on the step/"
         "request path; readers pay, so hoist the read off the hot loop "
         "(the series sampler thread is the periodic reader)"),
    # --------------------- interprocedural concurrency (analysis/locks.py)
    Rule("GL701", "guarded-field-unlocked-access", CAT_LOCK, ERROR,
         "read or write of a lock-guarded attribute (inferred from "
         "locked writes, or declared via `# graft: guarded-by(<lock>)`) "
         "with the guarding lock provably not held on any analyzed call "
         "path — held locksets propagate interprocedurally through "
         "helper calls, so a locked caller keeps a bare helper quiet"),
    Rule("GL702", "lock-order-inversion", CAT_LOCK, ERROR,
         "cycle in the global lock-acquisition-order graph: lock B is "
         "acquired while A is held on one path and A while B is held on "
         "another — two threads interleaving those paths deadlock; the "
         "related locations name the opposing acquisition sites"),
    Rule("GL703", "lock-held-across-dispatch", CAT_LOCK, WARNING,
         "blocking call (.block_until_ready()/time.sleep/queue/future/"
         "HTTP wait) inside a held-lock region in a hot module — every "
         "thread contending on that lock stalls behind a device or I/O "
         "wait; cond.wait() on the held lock itself is exempt (it "
         "releases the lock)"),
    Rule("GL704", "callback-escapes-lock", CAT_LOCK, WARNING,
         "closure capturing lock-guarded state registered as a callback "
         "or thread target without re-acquiring the guard inside the "
         "closure — it runs later on another thread, outside whatever "
         "lock was held at registration time"),
    # --------------- interprocedural sharding/donation (analysis/shardflow.py)
    Rule("GL801", "use-after-donate", CAT_SHARDING, ERROR,
         "read or pass of a value after it was handed to a donated "
         "argument position of a jitted call (donate_argnums) — the "
         "buffer is dead by contract; XLA may already have aliased it "
         "into the output, so the read returns garbage or raises; "
         "donation facts propagate through resolved helper calls, and "
         "the related location names the donating call site"),
    Rule("GL802", "cross-spec-combine", CAT_SHARDING, WARNING,
         "binop/concat/stack of values whose placement provenance "
         "differs (distinct with_sharding_constraint/device_put specs) "
         "— GSPMD inserts an implicit resharding collective at the "
         "combine point; constrain both operands to one spec, or make "
         "the reshard explicit; related locations name the two "
         "placement sites"),
    Rule("GL803", "jit-pytree-churn", CAT_SHARDING, WARNING,
         "the same jitted callee is invoked with differing pytree "
         "structure across call sites (dict key order, list-vs-tuple) — "
         "the jit cache keys on treedef, so each structure is a silent "
         "full recompile GL101–103 cannot see; canonicalize the "
         "container at the call sites (related location names the "
         "other one)"),
    Rule("GL804", "device-value-serialized", CAT_SHARDING, ERROR,
         "device-tainted value reaches a serialization sink "
         "(json.dumps/pickle/struct.pack/base64/.tobytes()) without an "
         "np.asarray()/jax.device_get() laundering point — the wire "
         "format captures a live device buffer (undefined bytes under "
         "donation, a forced sync at best); copy to host first, the "
         "fleet KV-handoff contract"),
    Rule("GL805", "collective-axis-literal", CAT_SHARDING, WARNING,
         "psum/all_gather/ppermute axis name passed as a string "
         "literal outside parallel/mesh.py — axis names are the mesh "
         "spine's contract; a literal drifts silently when the mesh "
         "axes are renamed or reshaped, so read them from the active "
         "MeshContext / parallel.mesh constants"),
)

RULES: Dict[str, Rule] = {r.id: r for r in _ALL}

#: Runtime cross-check: when a *runtime* monitor fires, these are the
#: static rules that should have caught the pattern before it shipped.
#: observe/watchdog.py and observe/syncmon.py tag their events with
#: these ids so a production alert points straight back at graft-lint.
RUNTIME_RULE_HINTS: Dict[str, Tuple[str, ...]] = {
    "recompile": ("GL101", "GL102", "GL103"),
    "host_sync": ("GL001", "GL002", "GL201", "GL202", "GL203"),
    "span_taint": ("GL601",),
    "hot_snapshot": ("GL602",),
    "lock_order": ("GL702",),
    "guarded_field": ("GL701",),
    "use_after_donate": ("GL801",),
    "device_serialized": ("GL804",),
    "reshard": ("GL802",),
}


def runtime_hint(event_kind: str) -> str:
    """Human-facing 'GL101/GL102/GL103' tag for a runtime event kind."""
    return "/".join(RUNTIME_RULE_HINTS.get(event_kind, ()))
