"""graft-lint output renderers: text (human), JSON (tools/
lint_report.py), SARIF 2.1.0 (code-scanning UIs).

The JSON schema is contractual — `tools/lint_report.py` and the tests
round-trip it:

    {"tool": "graft-lint", "version": ..., "summary": {"files": N,
     "findings": N, "errors": N, "warnings": N, "baselined": N,
     "by_rule": {"GL202": N, ...}},
     "findings": [Finding.to_dict(), ...]}
"""

from __future__ import annotations

import json
from collections import Counter
from typing import List

from deeplearning4j_tpu.analysis.engine import Finding
from deeplearning4j_tpu.analysis.rules import ERROR, RULES

TOOL_NAME = "graft-lint"
TOOL_VERSION = "1.0.0"
TOOL_URI = ("https://github.com/deeplearning4j/deeplearning4j"
            "#graft-lint")


def summarize(findings: List[Finding], *, files: int = 0,
              baselined: int = 0) -> dict:
    by_rule = Counter(f.rule for f in findings)
    errors = sum(1 for f in findings if f.severity == ERROR)
    return {"files": files, "findings": len(findings),
            "errors": errors, "warnings": len(findings) - errors,
            "baselined": baselined,
            "by_rule": dict(sorted(by_rule.items()))}


def render_text(findings: List[Finding], *, files: int = 0,
                baselined: int = 0) -> str:
    lines = []
    for f in findings:
        meta = f.meta
        lines.append(f"{f.path}:{f.line}:{f.col + 1} "
                     f"{f.rule}[{meta.severity}] {meta.name}: "
                     f"{f.message}")
        if f.snippet:
            lines.append(f"    | {f.snippet}")
    s = summarize(findings, files=files, baselined=baselined)
    lines.append(
        f"graft-lint: {s['findings']} finding(s) "
        f"({s['errors']} error(s), {s['warnings']} warning(s)) "
        f"in {files} file(s); {baselined} baselined")
    return "\n".join(lines) + "\n"


def render_json(findings: List[Finding], *, files: int = 0,
                baselined: int = 0) -> str:
    doc = {"tool": TOOL_NAME, "version": TOOL_VERSION,
           "summary": summarize(findings, files=files,
                                baselined=baselined),
           "findings": [f.to_dict() for f in findings]}
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


def render_sarif(findings: List[Finding], *, files: int = 0,
                 baselined: int = 0) -> str:
    rules_used = sorted({f.rule for f in findings} | set())
    sarif_rules = [
        {"id": rid, "name": RULES[rid].name,
         "shortDescription": {"text": RULES[rid].summary},
         "defaultConfiguration": {
             "level": RULES[rid].severity}}
        for rid in (rules_used or sorted(RULES))]
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": f.severity,
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1,
                               "snippet": {"text": f.snippet}},
                }}]}
        if f.related:
            # interprocedural findings (GL7xx) carry both ends: the
            # guard/lock site and the far access/acquisition site
            result["relatedLocations"] = [
                {"physicalLocation": {
                    "artifactLocation": {"uri": rp},
                    "region": {"startLine": rl}},
                 "message": {"text": rm}}
                for (rp, rl, rm) in f.related]
        results.append(result)
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": TOOL_NAME, "version": TOOL_VERSION,
                "informationUri": TOOL_URI,
                "rules": sarif_rules}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


RENDERERS = {"text": render_text, "json": render_json,
             "sarif": render_sarif}
