"""Baseline file support — checked-in intentional findings.

A baseline entry is the finding's line-number-insensitive identity
`(rule, path, snippet)` plus a count, so the baseline survives
unrelated edits (a finding only 'moves' in the baseline when the
offending line's *text* changes — at which point a human should
re-triage it anyway). `--baseline FILE` subtracts baselined findings
from the report; `--write-baseline FILE` regenerates the file from the
current tree, sorted, for a reviewable diff.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Tuple

from deeplearning4j_tpu.analysis.engine import Finding

BASELINE_VERSION = 1


def write_baseline(findings: List[Finding], path: str) -> dict:
    counts: Counter = Counter(f.key() for f in findings)
    entries = [
        {"rule": rule, "path": fpath, "snippet": snippet, "count": n}
        for (rule, fpath, snippet), n in sorted(counts.items())
    ]
    doc = {"version": BASELINE_VERSION, "tool": "graft-lint",
           "findings": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {doc.get('version')!r} "
            f"in {path} (expected {BASELINE_VERSION})")
    out: Dict[Tuple[str, str, str], int] = {}
    for e in doc.get("findings", ()):
        key = (e["rule"], e["path"], e.get("snippet", ""))
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def prune_baseline(findings: List[Finding], path: str,
                   ) -> Tuple[dict, List[dict]]:
    """Drop baseline entries in `path` that no longer match any current
    finding, clamping each surviving count to the number of findings
    that actually carry its key today. Rewrites the file in place and
    returns (new doc, pruned entries) — each pruned entry is the
    original dict plus how many counts were dropped, so the CLI can
    print exactly what went stale."""
    baseline = load_baseline(path)
    current: Counter = Counter(f.key() for f in findings)
    entries: List[dict] = []
    pruned: List[dict] = []
    for key, n in sorted(baseline.items()):
        rule, fpath, snippet = key
        keep = min(n, current.get(key, 0))
        if keep:
            entries.append({"rule": rule, "path": fpath,
                            "snippet": snippet, "count": keep})
        if n > keep:
            pruned.append({"rule": rule, "path": fpath,
                           "snippet": snippet, "count": n,
                           "dropped": n - keep})
    doc = {"version": BASELINE_VERSION, "tool": "graft-lint",
           "findings": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc, pruned


def apply_baseline(findings: List[Finding],
                   baseline: Dict[Tuple[str, str, str], int],
                   ) -> Tuple[List[Finding], int]:
    """Returns (new findings, number suppressed by the baseline). When a
    key occurs more often than its baselined count, the excess is new."""
    budget = dict(baseline)
    new: List[Finding] = []
    used = 0
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            used += 1
        else:
            new.append(f)
    return new, used
