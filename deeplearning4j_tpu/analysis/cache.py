"""graft-lint result cache — `.graftlint-cache.json`.

The full-repo strict pass in CI re-parses and re-lints ~200 files on
every run even though a typical PR touches a handful. This module
caches per-file findings keyed by (mtime_ns, size, sha256) and the
whole-program interprocedural findings keyed by a digest over every
file's content hash, so a warm re-lint of an unchanged tree is a
stat()-only walk — no file reads, no AST parses, no fixpoints.

Invalidation is conservative and layered:

  * doc `version` — the cache file format itself (this module).
  * `rules_version` — rules.RULES_VERSION; any rule-semantics bump
    forces a cold re-lint even when no source changed.
  * `config` — the hot-prefix tuple; hot-gating changes per-file
    results, so a different configuration never reuses entries.
  * per file: `mtime_ns` + `size` fast path, falling back to sha256
    when the stat signature moved but content may not have (checkout
    churn, `touch`); a changed sha re-lints just that file.
  * program: sha256 over the sorted (path, file-sha) pairs; ANY
    changed/added/removed file re-runs the (shared, single-build)
    GL7xx + GL8xx whole-program pass — interprocedural findings in
    file A can be caused by an edit in file B, so per-file reuse is
    never attempted for them.

Findings round-trip through Finding.to_dict()/from_dict(); severity
and category are re-derived from the live rule registry on load.
Cache write failures are non-fatal (read-only checkouts, parallel CI
shards racing on the same file) — the lint result is always computed
correctly, the cache is only ever a speedup.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis.engine import (
    DEFAULT_HOT_PREFIXES, Finding, is_hot, lint_source)
from deeplearning4j_tpu.analysis.rules import RULES_VERSION

#: Default cache location (repo root, gitignored).
CACHE_FILE = ".graftlint-cache.json"

#: Format version of the cache document itself.
CACHE_VERSION = 1


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()


def _rel(path: str) -> str:
    """Same path normalization lint_file / Program.from_paths use, so
    cached finding paths are byte-identical to cold-pass ones."""
    rel = os.path.relpath(path).replace(os.sep, "/")
    if rel.startswith(".."):
        rel = path.replace(os.sep, "/")
    return rel


def _fresh_doc(config: str) -> dict:
    return {"version": CACHE_VERSION, "rules_version": RULES_VERSION,
            "config": config, "files": {}, "program": {}}


def load_cache(cache_path: str, config: str) -> dict:
    """Load the cache doc, discarding it wholesale on any version,
    rules-version, or configuration mismatch (or corruption)."""
    try:
        with open(cache_path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if (not isinstance(doc, dict)
                or doc.get("version") != CACHE_VERSION
                or doc.get("rules_version") != RULES_VERSION
                or doc.get("config") != config
                or not isinstance(doc.get("files"), dict)
                or not isinstance(doc.get("program"), dict)):
            return _fresh_doc(config)
        return doc
    except (OSError, ValueError):
        return _fresh_doc(config)


def save_cache(cache_path: str, doc: dict) -> bool:
    """Atomic best-effort write; returns False (never raises) when the
    location is unwritable — caching is an optimization, not a result."""
    try:
        d = os.path.dirname(os.path.abspath(cache_path))
        fd, tmp = tempfile.mkstemp(prefix=".graftlint-cache.",
                                   suffix=".tmp", dir=d)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, cache_path)
        except OSError:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        return True
    except OSError:
        return False


def _read(path: str) -> str:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return f.read()


def lint_files_cached(files: Sequence[str], *,
                      hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
                      cache_path: str = CACHE_FILE) -> List[Finding]:
    """Cache-aware equivalent of engine.lint_files.

    Unchanged files (stat signature, else sha) reuse stored findings
    without being read or parsed; the shared whole-program GL7xx+GL8xx
    pass reruns only when the file-set content digest moved. Returns
    the same unsorted, unfiltered finding list lint_files would.
    """
    from deeplearning4j_tpu.analysis.callgraph import Program
    from deeplearning4j_tpu.analysis.locks import analyze_lock_program
    from deeplearning4j_tpu.analysis.shardflow import (
        analyze_shardflow_program)

    config = "|".join(hot_prefixes)
    doc = load_cache(cache_path, config)
    old_files: Dict[str, dict] = doc["files"]
    new_files: Dict[str, dict] = {}
    dirty = False

    findings: List[Finding] = []
    # rel -> source, only for files we actually had to read this run.
    read_src: Dict[str, str] = {}
    order: List[str] = []  # rel paths in lint order (for Program build)

    for path in files:
        rel = _rel(path)
        order.append(rel)
        try:
            st = os.stat(path)
            sig = [st.st_mtime_ns, st.st_size]
        except OSError:
            sig = None
        entry = old_files.get(rel)
        if (entry is not None and sig is not None
                and entry.get("stat") == sig):
            # Warm fast path: no read, no parse.
            new_files[rel] = entry
            findings.extend(Finding.from_dict(d)
                            for d in entry["findings"])
            continue
        src = _read(path)
        read_src[rel] = src
        sha = _sha(src)
        if entry is not None and entry.get("sha") == sha:
            # Content unchanged, stat churned (touch/checkout): reuse
            # findings, refresh the stat signature.
            entry = dict(entry, stat=sig)
            new_files[rel] = entry
            findings.extend(Finding.from_dict(d)
                            for d in entry["findings"])
            dirty = True
            continue
        fnds = lint_source(src, rel, hot=is_hot(rel, hot_prefixes),
                           hot_prefixes=hot_prefixes, locks=False)
        new_files[rel] = {"stat": sig, "sha": sha,
                          "findings": [f.to_dict() for f in fnds]}
        findings.extend(fnds)
        dirty = True

    # Merge rather than replace: a --changed / subset run must not
    # evict the full-repo entries. Entries for files that vanished
    # from disk are pruned; everything else survives untouched.
    merged = dict(old_files)
    for rel in list(merged):
        if rel not in new_files and not os.path.exists(rel):
            del merged[rel]
            dirty = True
    merged.update(new_files)
    doc["files"] = merged

    prog_digest = _sha("\n".join(
        f"{rel}:{new_files[rel]['sha']}" for rel in sorted(new_files)))
    prog_entry = doc["program"]
    if prog_entry.get("digest") == prog_digest:
        findings.extend(Finding.from_dict(d)
                        for d in prog_entry["findings"])
    else:
        sources: List[Tuple[str, str]] = []
        for rel, path in zip(order, files):
            src = read_src.get(rel)
            if src is None:
                src = _read(path)
            sources.append((rel, src))
        prog = Program.from_sources(sources)
        pf = list(analyze_lock_program(prog, hot_prefixes=hot_prefixes))
        pf.extend(analyze_shardflow_program(prog,
                                            hot_prefixes=hot_prefixes))
        doc["program"] = {"digest": prog_digest,
                          "findings": [f.to_dict() for f in pf]}
        findings.extend(pf)
        dirty = True

    if dirty:
        save_cache(cache_path, doc)
    return findings
