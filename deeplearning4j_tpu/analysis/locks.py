"""Eraser-style interprocedural lockset analysis — the GL7xx family.

GL301 is intraprocedural and lock-blind: it flags `self.x = ...`
outside *any* `with`-lock, but it cannot see that `_take_batch` is
only ever called with `self._cv` held, nor that a field written under
`self._lock` in one method is read bare in a helper three calls away,
nor that KVSlotPool's Condition and the scheduler's lock are acquired
in opposite orders on two paths. This pass can. It runs over the whole
program at once (analysis/callgraph.py) and emits:

  GL701 guarded-field-unlocked-access — a read or write of a guarded
        attribute with the guarding lock provably not held on any
        analyzed call path. Guards come from two places: an explicit
        `# graft: guarded-by(<lock>)` on the attribute's `__init__`
        assignment, or inference — an attribute written under a held
        own-class lock outside `__init__` is guarded by that lock.
  GL702 lock-order-inversion — a cycle in the global lock-acquisition
        graph (lock B taken under lock A on one path, A under B on
        another), built from nested `with` scopes across the call
        graph. The static deadlock detector.
  GL703 lock-held-across-dispatch — a blocking call (device sync,
        time.sleep, queue/future/HTTP wait) inside a held-lock region
        in a hot module. `cond.wait()` on the *held* lock is exempt:
        Condition.wait releases it.
  GL704 callback-escapes-lock — a closure capturing guarded state
        registered as a callback / thread target without re-acquiring
        the guard inside the closure body (it runs later, on another
        thread, outside the lock that happened to be held at
        registration time).

Soundness posture: held locksets are *may*-sets — the union over every
resolved internal call site (`entry-held`), plus locks visibly taken in
the function body. GL701 therefore only fires when the guard is held on
NO analyzed path, which is exactly the "provably not held" criterion:
unresolved dynamic calls never invent a held lock, and a single locked
caller is enough to keep a helper quiet (annotate the contract with
`# graft: allow(GL701): caller holds ...` only when the analysis
cannot see the caller). Propagation is bounded
(callgraph.MAX_PROPAGATION_ROUNDS hops) so it terminates on recursion.

Suppression uses the engine's grammar: `# graft: allow(GL70x): reason`
on the flagged line or the contiguous comment block above it.

Lock identity is `ClassName.attr` for instance locks (`KVSlotPool._cv`,
`DecodeSessionManager._lock`) and `module._name` for module-level
locks — the same names observe/lockmon.py uses at runtime, so a static
GL702 pair and a runtime inversion witness are string-comparable.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.callgraph import (
    MAX_PROPAGATION_ROUNDS, CallGraph, ClassInfo, FunctionInfo,
    ModuleInfo, Program,
)
from deeplearning4j_tpu.analysis.engine import (
    DEFAULT_HOT_PREFIXES, Finding, _collect_suppressions, _MUTATOR_METHODS,
    _terminal, is_hot, suppression_covers,
)

_GUARDED_BY_RE = re.compile(
    r"#\s*graft:\s*guarded-by\(\s*([A-Za-z_][\w]*)\s*\)")

# Blocking terminals that always count (receiver-independent).
_BLOCKING_ALWAYS = frozenset({
    "block_until_ready", "sleep", "urlopen", "getresponse",
    "recv", "accept", "connect",
})
# Blocking terminals that wait on their *receiver*: exempt when the
# receiver is the held lock itself (Condition.wait releases it).
_BLOCKING_ON_RECEIVER = frozenset({"wait", "wait_for", "result", "join"})
# `.get()` blocks only on queue-ish receivers with Queue.get's shape
# (no positional args — dict.get(key) has one).
_QUEUEISH_RE = re.compile(r"(^|_)(queue|events?|inbox|mailbox)($|s$|_)",
                          re.IGNORECASE)

# Callback/thread registrars: a closure handed to one of these outlives
# the registering call — and any lock held at registration time.
_REGISTRARS = frozenset({
    "add_done_callback", "Thread", "Timer", "submit", "add_deploy_hook",
    "call_soon", "call_soon_threadsafe", "call_later", "start_new_thread",
})
_CALLBACK_KWARGS = frozenset({"target", "callback", "func", "fn", "cb",
                              "on_done", "hook"})


@dataclass
class _Access:
    owner: ClassInfo
    attr: str
    node: ast.AST
    held: FrozenSet[str]
    write: bool
    via: str          # rendered receiver, e.g. "self" or "self.pool"


@dataclass
class _Acq:
    lock: str
    node: ast.AST
    held: FrozenSet[str]          # held *before* this acquisition


@dataclass
class _CallRec:
    callees: Tuple[str, ...]      # callee qualnames
    held: FrozenSet[str]


@dataclass
class _Block:
    node: ast.AST
    held: FrozenSet[str]
    what: str
    receiver_lock: Optional[str]  # lock id the call waits on, if any


@dataclass
class _Escape:
    reg_node: ast.AST             # the registrar call site
    registrar: str
    accesses: List[_Access]       # accesses inside the closure;
                                  # held = locks taken *inside* it


@dataclass
class _FnScan:
    fn: FunctionInfo
    accesses: List[_Access] = field(default_factory=list)
    acqs: List[_Acq] = field(default_factory=list)
    calls: List[_CallRec] = field(default_factory=list)
    blocks: List[_Block] = field(default_factory=list)
    escapes: List[_Escape] = field(default_factory=list)


class _FnWalker:
    """One pass over a function body, tracking the locally-held lockset
    through `with` scopes and acquire()/release() pairs."""

    def __init__(self, fn: FunctionInfo, graph: CallGraph,
                 *, closure_of: Optional["_FnWalker"] = None):
        self.fn = fn
        self.graph = graph
        self.held: List[str] = []
        self.scan = _FnScan(fn)
        # closure bodies get their own walker (fresh held set — they run
        # later); accesses land in buckets keyed by the closure node.
        self.closure_buckets: Dict[int, List[_Access]] = (
            closure_of.closure_buckets if closure_of is not None else {})
        self.local_defs: Dict[str, ast.AST] = (
            closure_of.local_defs if closure_of is not None else {})
        self.in_closure = closure_of is not None

    # ------------------------------------------------------------ entry
    def run(self) -> _FnScan:
        for stmt in self.fn.node.body:
            self._stmt(stmt)
        return self.scan

    def _held_now(self) -> FrozenSet[str]:
        return frozenset(self.held)

    # ------------------------------------------------- lock identities
    def _lock_id(self, e: ast.AST) -> Optional[str]:
        fn, cls = self.fn, self.fn.cls
        # self._lock / self._cv
        if (cls is not None and isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == fn.self_name):
            if e.attr in cls.lock_attrs:
                return f"{cls.name}.{e.attr}"
            return None
        # self.pool._cv through a typed attribute
        if (cls is not None and isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Attribute)
                and isinstance(e.value.value, ast.Name)
                and e.value.value.id == fn.self_name):
            tcls = self.graph.attr_class(cls, e.value.attr)
            if tcls is not None and e.attr in tcls.lock_attrs:
                return f"{tcls.name}.{e.attr}"
            return None
        # with self.pool.lock():  — a lock-getter method
        if isinstance(e, ast.Call):
            for cand in self.graph.resolve(fn, e):
                got = _lock_getter(cand)
                if got is not None:
                    return got
            return None
        # module-global lock
        if isinstance(e, ast.Name):
            return fn.module.module_locks.get(e.id)
        return None

    # -------------------------------------------------------- statements
    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                lid = self._lock_id(item.context_expr)
                self._expr(item.context_expr)
                if lid is not None:
                    self._note_acquire(lid, node)
                    self.held.append(lid)
                    acquired.append(lid)
            for s in node.body:
                self._stmt(s)
            for _ in acquired:
                self.held.pop()
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def = closure: runs later, with NO inherited locks
            self.local_defs[node.name] = node
            self._scan_closure(node, node.body)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                self._target(t, node)
            self._expr(node.value)
        elif isinstance(node, ast.AugAssign):
            self._target(node.target, node)
            self._access_expr(node.target, write=False)
            self._expr(node.value)
        elif isinstance(node, ast.AnnAssign):
            self._target(node.target, node)
            if node.value is not None:
                self._expr(node.value)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                self._target(t, node)
        elif isinstance(node, ast.Expr):
            if not self._acquire_release_stmt(node.value):
                self._expr(node.value)
        elif isinstance(node, ast.Try):
            for s in node.body:
                self._stmt(s)
            for h in node.handlers:
                if h.type is not None:
                    self._expr(h.type)
                for s in h.body:
                    self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
            for s in node.finalbody:
                self._stmt(s)
        elif isinstance(node, (ast.If, ast.While)):
            self._expr(node.test)
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr(node.iter)
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
        elif isinstance(node, (ast.Return, ast.Raise)):
            val = getattr(node, "value", None) or getattr(node, "exc", None)
            if val is not None:
                self._expr(val)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self._stmt(child)
                elif isinstance(child, ast.expr):
                    self._expr(child)

    def _acquire_release_stmt(self, e: ast.AST) -> bool:
        """`self._lock.acquire()` holds until the matching `release()`
        (or function end — conservative may-held)."""
        if not (isinstance(e, ast.Call)
                and isinstance(e.func, ast.Attribute)
                and e.func.attr in ("acquire", "release")):
            return False
        lid = self._lock_id(e.func.value)
        if lid is None:
            return False
        if e.func.attr == "acquire":
            self._note_acquire(lid, e)
            self.held.append(lid)
        elif lid in self.held:
            self.held.remove(lid)
        return True

    def _note_acquire(self, lid: str, node: ast.AST) -> None:
        if self.in_closure:
            return                    # closure acquisitions are local
        self.scan.acqs.append(_Acq(lid, node, self._held_now()))

    # ------------------------------------------------------ access sites
    def _target(self, t: ast.AST, stmt: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e, stmt)
            return
        if isinstance(t, ast.Starred):
            self._target(t.value, stmt)
            return
        base = t
        while isinstance(base, ast.Subscript):
            self._expr(base.slice)
            base = base.value
        self._access_expr(base, write=True)
        # `self.a.b = ...` also *reads* self.a; chain walk handles it
        if isinstance(base, ast.Attribute):
            self._expr(base.value)

    def _access_expr(self, e: ast.AST, *, write: bool) -> None:
        """Record a guarded-attr access for `self.x` or `self.a.x`."""
        fn, cls = self.fn, self.fn.cls
        if cls is None or not isinstance(e, ast.Attribute):
            return
        if isinstance(e.value, ast.Name) and e.value.id == fn.self_name:
            if e.attr in cls.lock_attrs:
                return
            self._record_access(cls, e.attr, e, write, via=fn.self_name)
        elif (isinstance(e.value, ast.Attribute)
              and isinstance(e.value.value, ast.Name)
              and e.value.value.id == fn.self_name):
            tcls = self.graph.attr_class(cls, e.value.attr)
            if tcls is not None and e.attr not in tcls.lock_attrs:
                self._record_access(
                    tcls, e.attr, e, write,
                    via=f"{fn.self_name}.{e.value.attr}")

    def _record_access(self, owner: ClassInfo, attr: str, node: ast.AST,
                       write: bool, via: str) -> None:
        acc = _Access(owner, attr, node, self._held_now(), write, via)
        if self.in_closure:
            self.closure_buckets.setdefault(
                id(self._closure_root), []).append(acc)
        else:
            self.scan.accesses.append(acc)

    # ------------------------------------------------------- expressions
    def _expr(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, ast.Lambda):
            self._scan_closure(node, [node.body])
            return
        if isinstance(node, ast.Attribute):
            self._access_expr(node, write=False)
            self._expr(node.value)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _scan_closure(self, root: ast.AST, body: List[ast.AST]) -> None:
        sub = _FnWalker(self.fn, self.graph, closure_of=self)
        sub._closure_root = root
        sub.closure_buckets.setdefault(id(root), [])
        for item in body:
            if isinstance(item, ast.stmt):
                sub._stmt(item)
            else:
                sub._expr(item)

    def _call(self, node: ast.Call) -> None:
        func = node.func
        term = _terminal(func)
        held = self._held_now()

        # resolution edge for interprocedural propagation (not from
        # closures — they run on another thread/time with entry ∅)
        if not self.in_closure:
            callees = self.graph.resolve(self.fn, node)
            if callees:
                self.scan.calls.append(_CallRec(
                    tuple(c.qualname for c in callees), held))

        # mutator call on a guarded attr: a write
        if isinstance(func, ast.Attribute) \
                and func.attr in _MUTATOR_METHODS:
            self._access_expr(func.value, write=True)

        # blocking-call detection (GL703) — skip inside closures (the
        # registration-time lock is not held when the closure runs)
        if not self.in_closure and held:
            self._check_blocking(node, term, held)

        # walk children (fills closure buckets for lambda args)
        if isinstance(func, ast.Attribute):
            self._expr(func.value)
        elif isinstance(func, (ast.Call, ast.Lambda)):
            self._expr(func)
        for a in node.args:
            self._expr(a)
        for k in node.keywords:
            self._expr(k.value)

        # callback-escape detection (GL704): closures handed to a
        # registrar, with or without a lock held — the closure must
        # re-acquire its guard either way
        if term in _REGISTRARS:
            cands = list(node.args) + [
                k.value for k in node.keywords
                if k.arg in _CALLBACK_KWARGS]
            for cand in cands:
                closure = None
                if isinstance(cand, ast.Lambda):
                    closure = cand
                elif isinstance(cand, ast.Name) \
                        and cand.id in self.local_defs:
                    closure = self.local_defs[cand.id]
                if closure is None:
                    continue
                accesses = self.closure_buckets.get(id(closure), [])
                if accesses:
                    self.scan.escapes.append(
                        _Escape(node, term or "?", accesses))

    def _check_blocking(self, node: ast.Call, term: Optional[str],
                        held: FrozenSet[str]) -> None:
        func = node.func
        if term in _BLOCKING_ALWAYS:
            self.scan.blocks.append(_Block(node, held, f"{term}()", None))
            return
        if not isinstance(func, ast.Attribute):
            return
        if term in _BLOCKING_ON_RECEIVER:
            rlock = self._lock_id(func.value)
            self.scan.blocks.append(
                _Block(node, held, f".{term}()", rlock))
        elif term == "get" and not node.args:
            recv = _terminal(func.value) or ""
            if _QUEUEISH_RE.search(recv):
                self.scan.blocks.append(
                    _Block(node, held, f"{recv}.get()", None))


def _lock_getter(meth: FunctionInfo) -> Optional[str]:
    """`def lock(self): return self._cv` -> 'Cls._cv'."""
    if meth.cls is None:
        return None
    for stmt in meth.node.body:
        if (isinstance(stmt, ast.Return)
                and isinstance(stmt.value, ast.Attribute)
                and isinstance(stmt.value.value, ast.Name)
                and stmt.value.value.id == meth.self_name
                and stmt.value.attr in meth.cls.lock_attrs):
            return f"{meth.cls.name}.{stmt.value.attr}"
    return None


# -------------------------------------------------------------- guards

@dataclass
class _Guard:
    lock: str                     # "Cls._lock"
    site: Tuple[str, int, str]    # (path, line, evidence message)
    explicit: bool


def _explicit_guards(ci: ClassInfo) -> Dict[str, _Guard]:
    """`self.x = ... # graft: guarded-by(_lock)` annotations, on the
    assignment line or the contiguous comment block above it."""
    out: Dict[str, _Guard] = {}
    init = ci.methods.get("__init__")
    if init is None:
        return out
    lines = ci.module.lines
    for n in ast.walk(init.node):
        if not isinstance(n, ast.Assign):
            continue
        for t in n.targets:
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == ci.self_name):
                continue
            cand = [n.lineno]
            ln = n.lineno - 1
            while ln >= 1 and lines[ln - 1].lstrip().startswith("#"):
                cand.append(ln)
                ln -= 1
            for cl in cand:
                m = _GUARDED_BY_RE.search(lines[cl - 1]) \
                    if 0 < cl <= len(lines) else None
                if m:
                    lock_attr = m.group(1)
                    out[t.attr] = _Guard(
                        f"{ci.name}.{lock_attr}",
                        (ci.module.path, n.lineno,
                         f"declared `guarded-by({lock_attr})` here"),
                        explicit=True)
                    break
    return out


def _infer_guards(prog: Program, scans: Dict[str, _FnScan],
                  entry: Dict[str, FrozenSet[str]],
                  ) -> Dict[str, Dict[str, _Guard]]:
    """attr -> guard per class: explicit annotations, plus inference —
    an attribute *written* under a held own-class lock outside __init__
    is guarded by that lock (majority lock wins on ties)."""
    guards: Dict[str, Dict[str, _Guard]] = {}
    votes: Dict[Tuple[str, str], Dict[str, Tuple[int, Tuple]]] = {}
    for scan in scans.values():
        fn = scan.fn
        if fn.name == "__init__":
            continue
        eff_entry = entry.get(fn.qualname, frozenset())
        for acc in scan.accesses:
            if not acc.write:
                continue
            own_prefix = f"{acc.owner.name}."
            for lid in acc.held | eff_entry:
                if not lid.startswith(own_prefix):
                    continue
                key = (acc.owner.qualname, acc.attr)
                cnt, site = votes.setdefault(key, {}).get(lid, (0, None))
                if site is None:
                    site = (fn.module.path, acc.node.lineno,
                            f"written here under `{lid}`")
                votes[key][lid] = (cnt + 1, site)
    for ci in (c for m in prog.modules.values()
               for c in m.classes.values()):
        cls_guards = _explicit_guards(ci)
        for (cq, attr), by_lock in votes.items():
            if cq != ci.qualname or attr in cls_guards:
                continue
            lid, (cnt, site) = max(by_lock.items(),
                                   key=lambda kv: (kv[1][0], kv[0]))
            cls_guards[attr] = _Guard(lid, site, explicit=False)
        if cls_guards:
            guards[ci.qualname] = cls_guards
    return guards


# ------------------------------------------------------------ the pass

def _propagate_entry(scans: Dict[str, _FnScan],
                     ) -> Dict[str, FrozenSet[str]]:
    """entry-held[f] = union over resolved internal call sites of
    (caller's locks at the site ∪ caller's own entry-held). Bounded
    fixpoint — each round moves facts one call edge."""
    entry: Dict[str, Set[str]] = {q: set() for q in scans}
    for _ in range(MAX_PROPAGATION_ROUNDS):
        changed = False
        for q, scan in scans.items():
            mine = entry[q]
            for rec in scan.calls:
                eff = rec.held | mine
                if not eff:
                    continue
                for callee in rec.callees:
                    tgt = entry.get(callee)
                    if tgt is not None and not eff <= tgt:
                        tgt |= eff
                        changed = True
        if not changed:
            break
    return {q: frozenset(s) for q, s in entry.items()}


def _snippet(mod: ModuleInfo, line: int) -> str:
    if 0 < line <= len(mod.lines):
        return mod.lines[line - 1].strip()
    return ""


class _LockAnalysis:
    def __init__(self, prog: Program, *, hot: Optional[bool],
                 hot_prefixes: Sequence[str]):
        self.prog = prog
        self.graph = CallGraph(prog)
        self.hot = hot
        self.hot_prefixes = hot_prefixes
        self.findings: List[Finding] = []
        self._allow: Dict[str, Dict[int, Set[str]]] = {}

    def run(self) -> List[Finding]:
        scans: Dict[str, _FnScan] = {}
        for fn in self.prog.functions.values():
            scans[fn.qualname] = _FnWalker(fn, self.graph).run()
        entry = _propagate_entry(scans)
        guards = _infer_guards(self.prog, scans, entry)
        self._gl701(scans, entry, guards)
        self._gl702(scans, entry)
        self._gl703(scans, entry)
        self._gl704(scans, guards)
        self.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return self.findings

    # ------------------------------------------------------------- emit
    def _emit(self, rule: str, mod: ModuleInfo, node: ast.AST,
              message: str,
              related: Sequence[Tuple[str, int, str]] = ()) -> None:
        line = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", line) or line
        allow = self._allow.setdefault(
            mod.path, _collect_suppressions(mod.lines))
        if suppression_covers(mod.lines, allow, rule, line, end):
            return
        self.findings.append(Finding(
            rule, mod.path, line, getattr(node, "col_offset", 0),
            message, _snippet(mod, line), related=tuple(related)))

    # ------------------------------------------------------------ GL701
    def _gl701(self, scans, entry, guards) -> None:
        seen: Set[Tuple[str, int, str]] = set()
        for scan in scans.values():
            fn = scan.fn
            if fn.name == "__init__":
                continue              # construction precedes publication
            eff_entry = entry.get(fn.qualname, frozenset())
            for acc in scan.accesses:
                g = guards.get(acc.owner.qualname, {}).get(acc.attr)
                if g is None or g.lock in acc.held | eff_entry:
                    continue
                dk = (fn.qualname, acc.node.lineno, acc.attr)
                if dk in seen:
                    continue
                seen.add(dk)
                kind = "write" if acc.write else "read"
                how = ("declared" if g.explicit else "inferred from "
                       "locked writes")
                self._emit(
                    "GL701", fn.module, acc.node,
                    f"{kind} of `{acc.via}.{acc.attr}` "
                    f"(`{acc.owner.name}.{acc.attr}`, guarded by "
                    f"`{g.lock}` — {how}) with the lock provably not "
                    f"held on any analyzed call path into "
                    f"`{fn.name}()`",
                    related=[g.site])

    # ------------------------------------------------------------ GL702
    def _gl702(self, scans, entry) -> None:
        # edge a->b: b acquired while a held (locally or entry-held)
        edges: Dict[Tuple[str, str], Tuple[ModuleInfo, ast.AST]] = {}
        for scan in scans.values():
            fn = scan.fn
            eff_entry = entry.get(fn.qualname, frozenset())
            for acq in scan.acqs:
                for h in acq.held | eff_entry:
                    if h != acq.lock:
                        edges.setdefault((h, acq.lock),
                                         (fn.module, acq.node))
        cycles = _find_cycles(set(edges))
        reported: Set[FrozenSet[str]] = set()
        for cyc in cycles:
            key = frozenset(cyc)
            if key in reported:
                continue
            reported.add(key)
            cyc_edges = [(a, b) for (a, b) in edges
                         if a in key and b in key]
            cyc_edges.sort(key=lambda e: (edges[e][1].lineno, e))
            (a0, b0) = cyc_edges[0]
            mod0, node0 = edges[(a0, b0)]
            related = []
            for (a, b) in cyc_edges[1:5]:
                m, n = edges[(a, b)]
                related.append((m.path, n.lineno,
                                f"`{b}` acquired here while `{a}` held"))
            order = " -> ".join(sorted(key))
            self._emit(
                "GL702", mod0, node0,
                f"lock-order inversion: cycle {order} -> "
                f"{sorted(key)[0]} in the global acquisition graph — "
                f"`{b0}` is acquired here while `{a0}` is held, and the "
                f"opposite order exists (see related locations); two "
                f"threads can deadlock",
                related=related)

    # ------------------------------------------------------------ GL703
    def _gl703(self, scans, entry) -> None:
        for scan in scans.values():
            fn = scan.fn
            hot = self.hot if self.hot is not None \
                else is_hot(fn.module.path, self.hot_prefixes)
            if not hot:
                continue
            eff_entry = entry.get(fn.qualname, frozenset())
            for blk in scan.blocks:
                eff = blk.held | eff_entry
                if not eff:
                    continue
                if blk.receiver_lock is not None \
                        and blk.receiver_lock in eff:
                    continue      # cond.wait() releases the held lock
                locks = ", ".join(sorted(eff))
                self._emit(
                    "GL703", fn.module, blk.node,
                    f"blocking call {blk.what} while holding "
                    f"`{locks}` in a hot module — every thread "
                    f"contending on the lock stalls behind this wait; "
                    f"move the blocking work outside the lock region")

    # ------------------------------------------------------------ GL704
    def _gl704(self, scans, guards) -> None:
        for scan in scans.values():
            fn = scan.fn
            for esc in scan.escapes:
                for acc in esc.accesses:
                    g = guards.get(acc.owner.qualname, {}).get(acc.attr)
                    if g is None or g.lock in acc.held:
                        continue
                    self._emit(
                        "GL704", fn.module, acc.node,
                        f"closure passed to {esc.registrar}(...) "
                        f"{'writes' if acc.write else 'reads'} "
                        f"`{acc.via}.{acc.attr}` (guarded by "
                        f"`{g.lock}`) without re-acquiring the lock — "
                        f"it runs later on another thread, outside any "
                        f"lock held at registration",
                        related=[(fn.module.path, esc.reg_node.lineno,
                                  "registered here"),
                                 g.site])
                    break         # one finding per escaped closure


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Strongly-connected components of size >= 2 (Tarjan, iterative).
    Any SCC with two or more locks contains an acquisition-order cycle."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            recurse = False
            neighbors = adj[v]
            for i in range(pi, len(neighbors)):
                w = neighbors[i]
                if w not in index:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) >= 2:
                    sccs.append(sorted(scc))
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])

    for v in adj:
        if v not in index:
            strongconnect(v)
    return sccs


# ------------------------------------------------------------ public API

def analyze_lock_program(prog: Program, *,
                         hot: Optional[bool] = None,
                         hot_prefixes: Sequence[str] =
                         DEFAULT_HOT_PREFIXES) -> List[Finding]:
    """Run the GL7xx lockset pass over an already-built Program.

    This is the seam the engine uses to share ONE callgraph build
    between the lockset and shardflow families — building the Program
    (parse + symbol tables) dominates a whole-repo run, so each
    interprocedural pass must accept a prebuilt one rather than
    re-parsing the world per family."""
    return _LockAnalysis(prog, hot=hot, hot_prefixes=hot_prefixes).run()


def analyze_lock_sources(sources: Sequence[Tuple[str, str]], *,
                         hot: Optional[bool] = None,
                         hot_prefixes: Sequence[str] =
                         DEFAULT_HOT_PREFIXES) -> List[Finding]:
    """Run the GL7xx lockset pass over (path, source) pairs as one
    program. `hot` forces GL703's hot gate for every file (fixtures)."""
    prog = Program.from_sources(sources)
    return analyze_lock_program(prog, hot=hot, hot_prefixes=hot_prefixes)


def analyze_lock_paths(files: Sequence[str], *,
                       hot_prefixes: Sequence[str] =
                       DEFAULT_HOT_PREFIXES) -> List[Finding]:
    prog = Program.from_paths(files)
    return analyze_lock_program(prog, hot=None, hot_prefixes=hot_prefixes)
