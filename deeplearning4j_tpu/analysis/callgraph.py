"""Whole-program call graph for graft-lint's interprocedural passes.

The GL7xx lockset analysis (analysis/locks.py) needs to know, for a
call site like `self.pool.free(slot)` or a bare `helper(x)`, WHICH
function body runs — across modules. This module builds that map from
plain ASTs, stdlib-only, with deliberately-bounded resolution:

- module-level functions: same-module calls, `from mod import f`, and
  `mod.f(...)` through an import alias;
- methods: `self.m(...)` resolved through the enclosing class and its
  program-local bases (depth-first, cycle-safe);
- one level of attribute typing: `self.pool = KVSlotPool(...)` in
  `__init__` types `self.pool`, so `self.pool.free(...)` resolves into
  KVSlotPool — the cross-class seam the lock analysis cares about
  (KVSlotPool's Condition is acquired from serving/sessions.py);
- constructors: `ClassName(...)` resolves to `__init__`.

Anything else (duck-typed parameters, dynamic dispatch, builtins)
deliberately resolves to *nothing*: the lockset pass treats unresolved
calls as opaque, which keeps it sound-for-suppression — a held lock is
never invented for code we cannot see — and keeps the whole-repo build
cheap enough for the CI lint gate.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: threading constructors whose result is a lock-ish guard object, and
#: the attribute-name heuristic for guard attributes (shared with the
#: engine's intraprocedural GL301).
LOCK_CLASSES = ("Lock", "RLock", "Condition", "Semaphore",
                "BoundedSemaphore")
LOCKISH_RE = re.compile(
    r"(^|_)r?lock|mutex|(^|_)cv($|_)|(^|_)cond(ition)?($|_)",
    re.IGNORECASE)

#: Interprocedural propagation is bounded: held-lockset facts travel at
#: most this many call-graph hops (each fixpoint round moves facts one
#: edge). Plenty for this codebase; guarantees termination regardless.
MAX_PROPAGATION_ROUNDS = 16


def module_name_from_path(path: str) -> str:
    """'deeplearning4j_tpu/serving/sessions.py' -> dotted module name."""
    norm = path.replace(os.sep, "/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    if norm.endswith("/__init__"):
        norm = norm[: -len("/__init__")]
    return norm.strip("/").replace("/", ".")


@dataclass
class FunctionInfo:
    qualname: str                       # "pkg.mod.Class.method"
    node: ast.AST                       # FunctionDef / AsyncFunctionDef
    module: "ModuleInfo"
    cls: Optional["ClassInfo"] = None

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def self_name(self) -> Optional[str]:
        if self.cls is None:
            return None
        args = self.node.args.args
        return args[0].arg if args else "self"


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    bases: List[str] = field(default_factory=list)     # dotted as written
    lock_attrs: Set[str] = field(default_factory=set)
    #: self.<attr> -> ClassInfo of the constructor assigned in __init__
    attr_classes: Dict[str, "ClassInfo"] = field(default_factory=dict)
    self_name: str = "self"

    @property
    def qualname(self) -> str:
        return f"{self.module.name}.{self.name}"


@dataclass
class ModuleInfo:
    path: str
    name: str                           # dotted
    source: str
    lines: List[str]
    tree: ast.Module
    #: local alias -> dotted module ("np" -> "numpy")
    import_alias: Dict[str, str] = field(default_factory=dict)
    #: local name -> (dotted module, original name) for from-imports
    from_names: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    #: module-global lock variables: name -> lock id "modshort.name"
    module_locks: Dict[str, str] = field(default_factory=dict)

    @property
    def shortname(self) -> str:
        return self.name.split(".")[-1]


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lock_ctor(value: ast.AST) -> bool:
    return (isinstance(value, ast.Call)
            and _terminal(value.func) in LOCK_CLASSES)


class Program:
    """All parsed modules, indexed for resolution."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}    # incl. methods

    # ------------------------------------------------------ construction
    @classmethod
    def from_sources(cls, sources: Sequence[Tuple[str, str]]) -> "Program":
        """Build from (path, source) pairs; unparsable files are skipped
        (the per-file engine already reports GL000 for them)."""
        prog = cls()
        for path, source in sources:
            try:
                tree = ast.parse(source)
            except SyntaxError:
                continue
            prog._add_module(path, source, tree)
        for mod in prog.modules.values():
            for ci in mod.classes.values():
                prog._scan_class_init(ci)
        return prog

    @classmethod
    def from_paths(cls, paths: Sequence[str]) -> "Program":
        sources = []
        for p in paths:
            try:
                with open(p, "r", encoding="utf-8", errors="replace") as f:
                    src = f.read()
            except OSError:
                continue
            rel = os.path.relpath(p).replace(os.sep, "/")
            if rel.startswith(".."):
                rel = p.replace(os.sep, "/")
            sources.append((rel, src))
        return cls.from_sources(sources)

    def _add_module(self, path: str, source: str, tree: ast.Module) -> None:
        mi = ModuleInfo(path=path, name=module_name_from_path(path),
                        source=source, lines=source.splitlines(),
                        tree=tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.import_alias[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:      # relative: resolve against this module
                    parts = mi.name.split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    mi.from_names[a.asname or a.name] = (base, a.name)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(f"{mi.name}.{stmt.name}", stmt, mi)
                mi.functions[stmt.name] = fi
                self.functions[fi.qualname] = fi
            elif isinstance(stmt, ast.ClassDef):
                ci = ClassInfo(stmt.name, mi, stmt)
                for b in stmt.bases:
                    dotted = _dotted(b)
                    if dotted:
                        ci.bases.append(dotted)
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fi = FunctionInfo(
                            f"{mi.name}.{stmt.name}.{sub.name}", sub, mi,
                            cls=ci)
                        ci.methods[sub.name] = fi
                        self.functions[fi.qualname] = fi
                mi.classes[stmt.name] = ci
            elif isinstance(stmt, ast.Assign):
                # module-global lock: `_lock = threading.Lock()`
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and (
                            _is_lock_ctor(stmt.value)
                            or (LOCKISH_RE.search(t.id)
                                and isinstance(stmt.value, ast.Call))):
                        if _is_lock_ctor(stmt.value):
                            mi.module_locks[t.id] = \
                                f"{mi.shortname}.{t.id}"
        self.modules[mi.name] = mi

    def _scan_class_init(self, ci: ClassInfo) -> None:
        init = ci.methods.get("__init__")
        if init is None:
            return
        if init.node.args.args:
            ci.self_name = init.node.args.args[0].arg
        for n in ast.walk(init.node):
            if not isinstance(n, ast.Assign):
                continue
            for t in n.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == ci.self_name):
                    continue
                if _is_lock_ctor(n.value) or LOCKISH_RE.search(t.attr):
                    ci.lock_attrs.add(t.attr)
                elif isinstance(n.value, ast.Call):
                    target = self._resolve_class(ci.module, n.value.func)
                    if target is not None:
                        ci.attr_classes[t.attr] = target

    # -------------------------------------------------------- resolution
    def _resolve_class(self, mod: ModuleInfo,
                       func: ast.AST) -> Optional[ClassInfo]:
        if isinstance(func, ast.Name):
            if func.id in mod.classes:
                return mod.classes[func.id]
            tgt = mod.from_names.get(func.id)
            if tgt is not None:
                tmod = self.modules.get(tgt[0])
                if tmod is not None:
                    return tmod.classes.get(tgt[1])
        elif isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            tmod_name = mod.import_alias.get(func.value.id)
            if tmod_name and tmod_name in self.modules:
                return self.modules[tmod_name].classes.get(func.attr)
        return None

    def resolve_base(self, ci: ClassInfo, base: str) -> Optional[ClassInfo]:
        mod = ci.module
        head = base.split(".")[0]
        if base in mod.classes:
            return mod.classes[base]
        tgt = mod.from_names.get(base)
        if tgt is not None:
            tmod = self.modules.get(tgt[0])
            if tmod is not None:
                return tmod.classes.get(tgt[1])
        if "." in base:
            tmod_name = mod.import_alias.get(head)
            if tmod_name and tmod_name in self.modules:
                return self.modules[tmod_name].classes.get(
                    base.split(".")[-1])
        return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class CallGraph:
    """Call-site resolution over a Program."""

    def __init__(self, program: Program):
        self.program = program

    def lookup_method(self, ci: ClassInfo, name: str,
                      _seen: Optional[Set[str]] = None,
                      ) -> Optional[FunctionInfo]:
        """Method resolution through program-local bases (DFS, cycle-
        and depth-safe)."""
        seen = _seen if _seen is not None else set()
        if ci.qualname in seen or len(seen) > 32:
            return None
        seen.add(ci.qualname)
        if name in ci.methods:
            return ci.methods[name]
        for base in ci.bases:
            bci = self.program.resolve_base(ci, base)
            if bci is not None:
                hit = self.lookup_method(bci, name, seen)
                if hit is not None:
                    return hit
        return None

    def attr_class(self, ci: ClassInfo, attr: str) -> Optional[ClassInfo]:
        cur: Optional[ClassInfo] = ci
        seen: Set[str] = set()
        while cur is not None and cur.qualname not in seen:
            seen.add(cur.qualname)
            if attr in cur.attr_classes:
                return cur.attr_classes[attr]
            nxt = None
            for base in cur.bases:
                nxt = self.program.resolve_base(cur, base)
                if nxt is not None:
                    break
            cur = nxt
        return None

    def resolve(self, fn: FunctionInfo,
                call: ast.Call) -> List[FunctionInfo]:
        """Candidate callee bodies for a call site (empty = opaque)."""
        func = call.func
        mod = fn.module
        if isinstance(func, ast.Name):
            name = func.id
            if name in mod.functions:
                return [mod.functions[name]]
            if name in mod.classes:
                init = mod.classes[name].methods.get("__init__")
                return [init] if init else []
            tgt = mod.from_names.get(name)
            if tgt is not None:
                tmod = self.program.modules.get(tgt[0])
                if tmod is not None:
                    if tgt[1] in tmod.functions:
                        return [tmod.functions[tgt[1]]]
                    if tgt[1] in tmod.classes:
                        init = tmod.classes[tgt[1]].methods.get("__init__")
                        return [init] if init else []
            return []
        if not isinstance(func, ast.Attribute):
            return []
        base, meth = func.value, func.attr
        # self.m(...)
        if (fn.cls is not None and isinstance(base, ast.Name)
                and base.id == fn.self_name):
            hit = self.lookup_method(fn.cls, meth)
            return [hit] if hit else []
        # self.attr.m(...) through a typed attribute
        if (fn.cls is not None and isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == fn.self_name):
            tcls = self.attr_class(fn.cls, base.attr)
            if tcls is not None:
                hit = self.lookup_method(tcls, meth)
                return [hit] if hit else []
            return []
        # mod.f(...) through an import alias
        if isinstance(base, ast.Name):
            tmod_name = mod.import_alias.get(base.id)
            if tmod_name and tmod_name in self.program.modules:
                tmod = self.program.modules[tmod_name]
                if meth in tmod.functions:
                    return [tmod.functions[meth]]
                if meth in tmod.classes:
                    init = tmod.classes[meth].methods.get("__init__")
                    return [init] if init else []
        return []
