"""graft-lint engine — AST analysis for tracer-safety, recompile
hazards, sync hygiene, and lock discipline.

This is a *linter*, not a type system: it runs a small intraprocedural
dataflow over each file and flags the patterns that have actually
bitten this codebase (the runtime RecompileWatchdog / HostSyncMonitor
catch the same failures after the fact; this pass catches them in
review). Three pieces of state drive every rule:

- **traced context** — a function is "traced" when jit/pmap/vmap/grad/
  checkpoint wraps it (decorator or call form) or it is passed as a
  body/cond to lax.scan / while_loop / fori_loop / cond / switch /
  map, or it is nested inside a traced function. Inside a traced
  function every parameter is a tracer; locals derived from tracers
  are tracked by a forward pass (`.shape`/`.ndim`/`.dtype`/`.size` and
  `len()` are static under trace and break the chain).
- **devicey values (host context)** — names assigned from calls rooted
  at a jax/jnp/lax import alias, from `*_jitted`-style callables, or
  arithmetic/indexing over such names. Host-side sync rules (GL2xx)
  only fire on devicey expressions, which keeps `int(os.environ[...])`
  and `np.asarray(request_json)` quiet.
- **lock ownership** — a class whose `__init__` creates a
  `threading.Lock/RLock/Condition` attribute (or any `*_lock`/`*_cv`
  attribute) declares its instance state lock-guarded; mutations of
  `self.*` outside a `with <lock>:` block are flagged (GL301).

Everything here is stdlib-only (ast + re), importable without jax —
same constraint as `observe/registry.py`, for the same reason: CI and
tooling must be able to run it anywhere.

Suppressions (same-line, or a comment line directly above):

    # graft: allow-sync(reason)      — suppresses sync-category rules
    # graft: allow(GL301): reason    — suppresses one rule id

A reason is mandatory; an empty reason leaves the finding live.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.rules import (
    CAT_SYNC, RULES, Rule,
)

#: Module prefixes declared hot (PERF_NOTES: ≤1 host sync per epoch /
#: no syncs on the serving dispatch path). Sync-hygiene rules (GL2xx)
#: only fire under these.
DEFAULT_HOT_PREFIXES: Tuple[str, ...] = (
    "deeplearning4j_tpu/optim",
    "deeplearning4j_tpu/serving",
    "deeplearning4j_tpu/parallel",
    "deeplearning4j_tpu/observe",
)

# wrapper terminal name -> positional slots holding traceable functions
_TRACE_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,), "pjit": (0,), "pmap": (0,), "vmap": (0,),
    "grad": (0,), "value_and_grad": (0,), "checkpoint": (0,),
    "remat": (0,), "custom_jvp": (0,), "custom_vjp": (0,),
    "scan": (0,), "map": (0,),
    "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2, 3), "switch": (1, 2, 3, 4, 5),
}

# the wrappers that own a *compile cache* keyed on function identity
_JIT_FAMILY = ("jit", "pjit", "pmap")

_STATIC_ATTRS = ("shape", "ndim", "dtype", "size", "aval", "sharding")
_MATERIALIZE_METHODS = ("item", "tolist")
_MUTATOR_METHODS = (
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
)
_LOG_METHODS = ("debug", "info", "warning", "warn", "error", "exception",
                "critical", "log")
# span-emitting callables (observe.trace / observe.reqtrace) whose kwargs
# are span attributes — GL601 requires those to be host scalars
_SPAN_EMITTERS = ("span", "emit_manual_span", "record_span",
                  "error_trace", "finish_root", "end_dispatch")

# full-registry/series reader methods (GL602): each walks every series
# and sorts histogram reservoirs — periodic-reader pricing only
_SNAPSHOT_READS = ("snapshot", "to_prometheus", "to_jsonl")
# receiver name tokens that mark a registry/series-store-ish object
_REGISTRYISH_TOKENS = frozenset(
    ("registry", "reg", "metrics", "series", "stats", "store"))
_LOCK_CLASSES = ("Lock", "RLock", "Condition", "Semaphore",
                 "BoundedSemaphore")

# word-ish boundaries: `_lock`/`lock`/`rlock`/`_cv`/`cond`/`mutex` are
# lock-ish; `block`/`blocks`/`max_seconds` are not.
_LOCKISH_RE = re.compile(
    r"(^|_)r?lock|mutex|(^|_)cv($|_)|(^|_)cond(ition)?($|_)",
    re.IGNORECASE)
_JITNAME_RE = re.compile(r"(^|_)jit(ted)?($|_)")

# jax-rooted calls whose result is a host int/bool/list, not a device
# array — `if jax.process_count() > 1:` is not a sync.
_HOST_RESULT_FUNCS = frozenset({
    "process_count", "process_index", "device_count",
    "local_device_count", "devices", "local_devices",
    "default_backend", "issubdtype", "result_type", "can_cast",
    "tree_structure", "tree_all",
})
# jax-rooted calls that return their inputs' leaves: device-valued iff
# an argument is (tree_map over host numpy stays host).
_TRANSPARENT_FUNCS = frozenset({
    "tree_map", "tree_leaves", "tree_flatten", "tree_unflatten",
    "tree_reduce", "tree_transpose",
})

_ALLOW_SYNC_RE = re.compile(
    r"#\s*graft:\s*allow-sync\(\s*([^)]*?)\s*\)")
_ALLOW_RULE_RE = re.compile(
    r"#\s*graft:\s*allow\(\s*(GL\d{3})\s*(?:[,:)]\s*([^)]*?))?\s*\)"
    r"(?::\s*(\S.*))?")
_COMMENT_LINE_RE = re.compile(r"^\s*#")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    #: far ends of an interprocedural finding (GL7xx): the lock/guard
    #: site, the opposing acquisition, the registration site — rendered
    #: as SARIF relatedLocations. (path, line, message) triples.
    related: Tuple[Tuple[str, int, str], ...] = ()

    @property
    def meta(self) -> Rule:
        return RULES[self.rule]

    @property
    def severity(self) -> str:
        return self.meta.severity

    def key(self) -> Tuple[str, str, str]:
        """Line-number-insensitive identity used by the baseline: the
        finding survives unrelated edits above it."""
        return (self.rule, self.path.replace(os.sep, "/"), self.snippet)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "name": self.meta.name,
                "category": self.meta.category,
                "severity": self.severity, "path": self.path,
                "line": self.line, "col": self.col,
                "message": self.message, "snippet": self.snippet,
                "related": [{"path": p, "line": ln, "message": m}
                            for (p, ln, m) in self.related]}

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        """Inverse of to_dict for the fields a Finding is built from —
        severity/category are re-derived from the rule registry, so a
        cached finding always reflects the CURRENT rule metadata."""
        return cls(d["rule"], d["path"], int(d["line"]), int(d["col"]),
                   d["message"], d.get("snippet", ""),
                   related=tuple((r["path"], int(r["line"]), r["message"])
                                 for r in d.get("related", ())))


def is_hot(path: str,
           hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES) -> bool:
    norm = path.replace(os.sep, "/")
    return any(p in norm for p in hot_prefixes)


# --------------------------------------------------------------- helpers

def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("list", "dict", "set")
    return False


class _Imports:
    """Per-module import aliases: which local names are jax-ish module
    roots, numpy roots, or bare from-jax function imports."""

    def __init__(self, tree: ast.Module):
        self.jax_roots: Set[str] = set()
        self.np_roots: Set[str] = set()
        self.from_jax: Set[str] = set()     # `from jax import jit` etc.
        self.partial_names: Set[str] = {"partial"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name == "jax" or a.name.startswith("jax."):
                        self.jax_roots.add(a.asname if a.asname
                                           else "jax")
                    elif a.name == "numpy" or a.name.startswith("numpy."):
                        self.np_roots.add(name)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    name = a.asname or a.name
                    if mod == "jax" and a.name == "numpy":
                        self.jax_roots.add(name)
                    elif mod.startswith("jax"):
                        if a.name in ("lax", "numpy"):
                            self.jax_roots.add(name)
                        else:
                            self.from_jax.add(name)
                    elif mod == "functools" and a.name == "partial":
                        self.partial_names.add(name)
                    elif mod == "numpy":
                        self.np_roots.add(name)

    # ------------------------------------------------------ provenance
    def is_jax_call_root(self, func: ast.AST) -> bool:
        """func resolves through a jax module alias (jnp.*, lax.*,
        jax.*.*) — device-producing unless the terminal says otherwise."""
        if isinstance(func, ast.Attribute):
            return _root_name(func) in self.jax_roots
        return False

    def wrapper_slots(self, func: ast.AST) -> Optional[Tuple[int, ...]]:
        """If `func` is a jax tracing wrapper, its function-arg slots."""
        term = _terminal(func)
        if term not in _TRACE_WRAPPERS:
            return None
        if isinstance(func, ast.Name) and term not in self.from_jax:
            return None
        if isinstance(func, ast.Attribute) \
                and _root_name(func) not in self.jax_roots:
            return None
        return _TRACE_WRAPPERS[term]

    def is_jit_family(self, func: ast.AST) -> bool:
        term = _terminal(func)
        if term not in _JIT_FAMILY:
            return False
        if isinstance(func, ast.Name):
            return term in self.from_jax
        return _root_name(func) in self.jax_roots

    def is_np_call(self, func: ast.AST, names: Tuple[str, ...]) -> bool:
        return (isinstance(func, ast.Attribute) and func.attr in names
                and _root_name(func) in self.np_roots)


def _collect_suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    """line number (1-based) -> {'cat:sync', 'GL301', ...}. A reason is
    mandatory; `allow-sync()` with no reason does not suppress."""
    allow: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        toks: Set[str] = set()
        m = _ALLOW_SYNC_RE.search(line)
        if m and m.group(1).strip():
            toks.add("cat:" + CAT_SYNC)
        m = _ALLOW_RULE_RE.search(line)
        if m and ((m.group(2) or "").strip() or (m.group(3) or "").strip()):
            toks.add(m.group(1))
        if toks:
            allow[i] = toks
    return allow


def suppression_covers(lines: List[str], allow: Dict[int, Set[str]],
                       rule: str, line: int, end: int) -> bool:
    """Shared suppression check: an `allow` token for `rule` (or its
    category) on any flagged line, or anywhere in the contiguous
    pure-comment block directly above (multi-line reasons). Used by the
    per-file walker and the interprocedural lockset pass alike."""
    covered = set(range(line, end + 1))
    ln = line - 1
    while ln >= 1 and _COMMENT_LINE_RE.match(lines[ln - 1]):
        covered.add(ln)
        ln -= 1
    cat_tok = "cat:" + RULES[rule].category
    for ln in covered:
        toks = allow.get(ln)
        if toks and (rule in toks or cat_tok in toks):
            return True
    return False


# ----------------------------------------------------------------- walker

@dataclass
class _Ctx:
    traced: bool = False
    tracked: Set[str] = field(default_factory=set)   # tracer-derived
    dev: Set[str] = field(default_factory=set)       # host device values
    fn_depth: int = 0
    loop_depth: int = 0
    lock_attrs: Optional[Set[str]] = None            # enclosing class's
    self_name: str = "self"
    lock_depth: int = 0
    in_init: bool = False


class _FileLinter:
    def __init__(self, path: str, source: str, *, hot: bool):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.hot = hot
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []
        self.allow = _collect_suppressions(self.lines)
        # names bound from get_registry()/get_series_store() — GL602
        # receiver tracking (file-wide, deliberately rough)
        self.registry_names: Set[str] = set()

    # ------------------------------------------------------------ entry
    def run(self) -> List[Finding]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError as e:
            self.findings.append(Finding(
                "GL000", self.path, e.lineno or 1, e.offset or 0,
                f"syntax error: {e.msg}", ""))
            return self.findings
        self.imports = _Imports(tree)
        self.module_defs: Dict[str, ast.AST] = {}
        self.traced_names: Set[str] = set()
        self.traced_lambdas: Set[int] = set()
        self._index(tree)
        ctx = _Ctx()
        for stmt in tree.body:
            self._stmt(stmt, ctx)
        self.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return self.findings

    def _index(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_defs.setdefault(node.name, node)
            elif isinstance(node, ast.Call):
                slots = self.imports.wrapper_slots(node.func)
                if slots is None:
                    continue
                for i in slots:
                    if i < len(node.args):
                        arg = node.args[i]
                        if isinstance(arg, ast.Name):
                            self.traced_names.add(arg.id)
                        elif isinstance(arg, ast.Lambda):
                            self.traced_lambdas.add(id(arg))

    # ------------------------------------------------------------- emit
    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", line) or line
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        f = Finding(rule, self.path, line, getattr(node, "col_offset", 0),
                    message, snippet)
        if suppression_covers(self.lines, self.allow, rule, line, end):
            self.suppressed.append(f)
            return
        self.findings.append(f)

    # ------------------------------------------------- taint predicates
    def _tainted(self, node: ast.AST, ctx: _Ctx) -> bool:
        """Tracer-derived *value* (static shape/dtype access breaks the
        chain) — drives the GL0xx rules inside traced functions."""
        if isinstance(node, ast.Name):
            return node.id in ctx.tracked
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._tainted(node.value, ctx)
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value, ctx)
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id == "len":
                return False
            if self.imports.is_jax_call_root(func):
                return True
            if isinstance(func, ast.Attribute) \
                    and self._tainted(func.value, ctx):
                return True
            if isinstance(func, ast.Name) and func.id in ctx.tracked:
                return True
            return any(self._tainted(a, ctx) for a in node.args) or \
                any(self._tainted(k.value, ctx) for k in node.keywords)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self._tainted(node.left, ctx)
                    or any(self._tainted(c, ctx) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return any(self._tainted(v, ctx) for v in node.values)
        if isinstance(node, ast.BinOp):
            return (self._tainted(node.left, ctx)
                    or self._tainted(node.right, ctx))
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand, ctx)
        if isinstance(node, ast.IfExp):
            return (self._tainted(node.body, ctx)
                    or self._tainted(node.orelse, ctx))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._tainted(e, ctx) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self._tainted(node.value, ctx)
        return False

    def _registryish(self, node: ast.AST) -> bool:
        """Is this receiver a MetricsRegistry / SeriesStore / stats
        aggregator? Matches direct get_registry()/get_series_store()
        call receivers, names bound from them, and receiver names built
        from registry-ish tokens (self.stats.registry, series_store…)."""
        if isinstance(node, ast.Call):
            return _terminal(node.func) in ("get_registry",
                                            "get_series_store")
        if isinstance(node, ast.Name) and node.id in self.registry_names:
            return True
        term = _terminal(node) or ""
        toks = re.split(r"[_\W]+", term.lower())
        return any(t in _REGISTRYISH_TOKENS for t in toks)

    def _devicey(self, node: ast.AST, ctx: _Ctx) -> bool:
        """Host-side 'this is (or contains) a live device array' — the
        precondition for the sync rules. Deliberately conservative:
        unknown function calls do NOT propagate, so ordinary host math
        stays quiet."""
        if isinstance(node, ast.Name):
            return node.id in ctx.dev
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._devicey(node.value, ctx)
        if isinstance(node, ast.Subscript):
            return self._devicey(node.value, ctx)
        if isinstance(node, ast.Call):
            func = node.func
            term = _terminal(func)
            if term == "device_get":
                return False                      # result lives on host
            if term in _HOST_RESULT_FUNCS:
                return False                      # host int/bool queries
            if term in _TRANSPARENT_FUNCS:
                # tree_map & friends return whatever their inputs hold
                return any(self._devicey(a, ctx) for a in node.args) \
                    or any(self._devicey(k.value, ctx)
                           for k in node.keywords)
            if self.imports.is_jax_call_root(func):
                return True
            if isinstance(func, ast.Name) and func.id in self.imports.from_jax:
                return True
            if term and _JITNAME_RE.search(term):
                return True                       # self._jitted(...) etc.
            if isinstance(func, ast.Attribute) \
                    and func.attr not in _MATERIALIZE_METHODS \
                    and self._devicey(func.value, ctx):
                return True                       # x.sum(), x.astype(...)
            return False
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self._devicey(node.left, ctx)
                    or any(self._devicey(c, ctx) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return any(self._devicey(v, ctx) for v in node.values)
        if isinstance(node, ast.BinOp):
            return (self._devicey(node.left, ctx)
                    or self._devicey(node.right, ctx))
        if isinstance(node, ast.UnaryOp):
            return self._devicey(node.operand, ctx)
        if isinstance(node, ast.IfExp):
            return (self._devicey(node.body, ctx)
                    or self._devicey(node.orelse, ctx))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._devicey(e, ctx) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self._devicey(node.value, ctx)
        return False

    def _dynamic_iter(self, it: ast.AST, ctx: _Ctx) -> bool:
        """GL005 wants positive evidence of *array* iteration: a bare
        tainted name is routinely a pytree dict (iterating its keys is
        host-side and legal), so only range()/enumerate()/zip() of a
        tracer and arithmetic/indexing-derived tracers count."""
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("range", "enumerate", "zip"):
            return any(self._tainted(a, ctx) for a in it.args)
        if isinstance(it, (ast.BinOp, ast.UnaryOp, ast.Subscript)):
            return self._tainted(it, ctx)
        return False

    def _update_bindings(self, targets: List[ast.AST], value_is: bool,
                         ctx: _Ctx) -> None:
        """Bind plain-name targets (incl. tuple unpacks) to the tracked/
        devicey set. Attribute/subscript targets are NOT bound — taint
        does not flow through `self.x = ...` (that would poison `self`)."""
        names = ctx.tracked if ctx.traced else ctx.dev
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, ast.Name):
                (names.add if value_is else names.discard)(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)

    # -------------------------------------------------------- statements
    def _stmt(self, node: ast.AST, ctx: _Ctx) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._function(node, ctx)
        elif isinstance(node, ast.ClassDef):
            self._class(node, ctx)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(node, ctx)
        elif isinstance(node, (ast.If, ast.While)):
            self._branch(node, ctx)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._for(node, ctx)
        elif isinstance(node, ast.Assert):
            if ctx.traced and self._tainted(node.test, ctx):
                self._emit("GL004", node,
                           "assert on a tracer-derived value inside a "
                           "traced function")
            self._expr(node.test, ctx)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            self._with(node, ctx)
        elif isinstance(node, ast.Try):
            self._try(node, ctx)
        elif isinstance(node, ast.Delete):
            self._check_lock_mutation_targets(node, node.targets, ctx)
            for t in node.targets:
                self._expr(t, ctx)
        elif isinstance(node, (ast.Return, ast.Expr)):
            if node.value is not None:
                self._expr(node.value, ctx)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self._expr(node.exc, ctx)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self._stmt(child, ctx)
                elif isinstance(child, ast.expr):
                    self._expr(child, ctx)

    def _body(self, stmts: List[ast.stmt], ctx: _Ctx) -> None:
        for s in stmts:
            self._stmt(s, ctx)

    def _assign(self, node: ast.AST, ctx: _Ctx) -> None:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        else:                                        # AnnAssign
            targets, value = [node.target], node.value
        self._check_lock_mutation_targets(node, targets, ctx)
        if (isinstance(value, ast.Call)
                and _terminal(value.func) in ("get_registry",
                                              "get_series_store")):
            for t in targets:
                if isinstance(t, ast.Name):
                    self.registry_names.add(t.id)
        if value is not None:
            self._expr(value, ctx)
            pred = self._tainted if ctx.traced else self._devicey
            is_tracked = pred(value, ctx)
            if isinstance(node, ast.AugAssign):
                if is_tracked:
                    self._update_bindings(targets, True, ctx)
            else:
                self._update_bindings(targets, is_tracked, ctx)

    def _branch(self, node, ctx: _Ctx) -> None:
        if ctx.traced and self._tainted(node.test, ctx):
            kw = "while" if isinstance(node, ast.While) else "if"
            self._emit("GL003", node.test,
                       f"Python `{kw}` on a tracer-derived value inside "
                       "a traced function — use lax.cond/lax.while_loop/"
                       "jnp.where")
        elif (not ctx.traced and self.hot
                and self._devicey(node.test, ctx)):
            kw = "while" if isinstance(node, ast.While) else "if"
            self._emit("GL202", node.test,
                       f"`{kw}` on a device value forces a blocking "
                       "device→host sync (implicit __bool__)")
        self._expr(node.test, ctx)
        if isinstance(node, ast.While):
            ctx.loop_depth += 1
            self._body(node.body, ctx)
            ctx.loop_depth -= 1
        else:
            self._body(node.body, ctx)
        self._body(node.orelse, ctx)

    def _for(self, node, ctx: _Ctx) -> None:
        if ctx.traced and self._dynamic_iter(node.iter, ctx):
            self._emit("GL005", node.iter,
                       "Python for-loop over a tracer-derived value "
                       "inside a traced function — use lax.scan/"
                       "lax.fori_loop")
        self._expr(node.iter, ctx)
        pred = self._tainted if ctx.traced else self._devicey
        self._update_bindings([node.target], pred(node.iter, ctx), ctx)
        ctx.loop_depth += 1
        self._body(node.body, ctx)
        ctx.loop_depth -= 1
        self._body(node.orelse, ctx)

    def _with(self, node, ctx: _Ctx) -> None:
        lockish = any(
            _LOCKISH_RE.search(_terminal(item.context_expr) or "")
            for item in node.items)
        for item in node.items:
            self._expr(item.context_expr, ctx)
        if lockish:
            ctx.lock_depth += 1
        self._body(node.body, ctx)
        if lockish:
            ctx.lock_depth -= 1

    def _try(self, node: ast.Try, ctx: _Ctx) -> None:
        self._body(node.body, ctx)
        for h in node.handlers:
            if h.type is None:
                self._emit("GL402", h,
                           "bare `except:` catches KeyboardInterrupt/"
                           "SystemExit and masks worker-thread errors")
            elif (len(h.body) == 1 and isinstance(h.body[0], ast.Pass)):
                self._emit("GL403", h,
                           "exception silently swallowed "
                           "(`except ...: pass`)")
            if h.type is not None:
                self._expr(h.type, ctx)
            self._body(h.body, ctx)
        self._body(node.orelse, ctx)
        self._body(node.finalbody, ctx)

    # --------------------------------------------------------- functions
    def _is_traced_def(self, node, ctx: _Ctx) -> bool:
        if ctx.traced:
            return True
        if node.name in self.traced_names:
            return True
        for dec in node.decorator_list:
            if self._jitish_decorator(dec):
                return True
        return False

    def _jitish_decorator(self, dec: ast.AST) -> Optional[ast.AST]:
        """The jit-ish callable node for a decorator, or None. Handles
        @jax.jit, @jit, @jax.jit(...), @partial(jax.jit, ...)."""
        if self.imports.wrapper_slots(dec) is not None:
            return dec
        if isinstance(dec, ast.Call):
            if self.imports.wrapper_slots(dec.func) is not None:
                return dec.func
            if (_terminal(dec.func) in self.imports.partial_names
                    and dec.args
                    and self.imports.wrapper_slots(dec.args[0]) is not None):
                return dec.args[0]
        return None

    def _jit_family_decorator(self, dec: ast.AST) -> bool:
        n = self._jitish_decorator(dec)
        return n is not None and _terminal(n) in _JIT_FAMILY

    def _static_param_names(self, call: ast.Call, fn) -> List[str]:
        """Parameter names pinned static by static_argnums/argnames on a
        jit call/decorator, resolved against `fn`'s signature."""
        names: List[str] = []
        params = [a.arg for a in
                  getattr(fn.args, "posonlyargs", []) + fn.args.args]
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value,
                                                                  str):
                        names.append(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value,
                                                                  int):
                        if 0 <= n.value < len(params):
                            names.append(params[n.value])
        return names

    def _check_static_args(self, call: ast.Call, fn) -> None:
        """GL101: static params whose defaults are unhashable."""
        static = self._static_param_names(call, fn)
        if not static:
            return
        args = getattr(fn.args, "posonlyargs", []) + fn.args.args
        defaults = fn.args.defaults
        offset = len(args) - len(defaults)
        by_name = {args[offset + i].arg: d
                   for i, d in enumerate(defaults)}
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if d is not None:
                by_name[a.arg] = d
        for name in static:
            d = by_name.get(name)
            if d is not None and _is_mutable_literal(d):
                self._emit("GL101", d,
                           f"static argument {name!r} has a mutable "
                           "(unhashable) default — jit cache keys hash "
                           "static args")

    def _function(self, node, ctx: _Ctx) -> None:
        for d in node.decorator_list:
            self._expr(d, ctx)
            jf = self._jitish_decorator(d)
            if jf is not None and _terminal(jf) in _JIT_FAMILY:
                if ctx.loop_depth > 0:
                    self._emit("GL103", node,
                               f"jit-decorated function {node.name!r} "
                               "defined inside a loop — a fresh compiled "
                               "program per iteration")
                elif ctx.fn_depth > 0 and not ctx.traced:
                    self._emit("GL102", node,
                               f"jit-decorated function {node.name!r} is "
                               "a fresh closure per enclosing call — the "
                               "jit cache keys on function identity, so "
                               "every call recompiles; hoist it or cache "
                               "the jitted callable")
                if isinstance(d, ast.Call):
                    self._check_static_args(d, node)
        for default in list(node.args.defaults) + \
                [d for d in node.args.kw_defaults if d is not None]:
            if _is_mutable_literal(default):
                self._emit("GL401", default,
                           f"mutable default argument in {node.name!r} — "
                           "shared across calls (and worker threads); "
                           "default to None")
            self._expr(default, ctx)

        inner = _Ctx(
            traced=self._is_traced_def(node, ctx),
            fn_depth=ctx.fn_depth + 1,
            lock_attrs=ctx.lock_attrs,
            self_name=ctx.self_name,
            lock_depth=0,
            in_init=(node.name == "__init__" and ctx.fn_depth == 0
                     and ctx.lock_attrs is not None),
        )
        if inner.traced:
            skip = ("self", "cls")
            for a in (getattr(node.args, "posonlyargs", [])
                      + node.args.args + node.args.kwonlyargs):
                if a.arg not in skip:
                    inner.tracked.add(a.arg)
            for a in (node.args.vararg, node.args.kwarg):
                if a is not None:
                    inner.tracked.add(a.arg)
        self._body(node.body, inner)

    def _class(self, node: ast.ClassDef, ctx: _Ctx) -> None:
        for d in node.decorator_list:
            self._expr(d, ctx)
        lock_attrs, self_name = self._find_lock_attrs(node)
        inner = _Ctx(fn_depth=0, lock_attrs=lock_attrs or None,
                     self_name=self_name)
        self._body(node.body, inner)

    def _find_lock_attrs(self, node: ast.ClassDef):
        lock_attrs: Set[str] = set()
        self_name = "self"
        for stmt in node.body:
            if isinstance(stmt, ast.FunctionDef) and \
                    stmt.name == "__init__":
                if stmt.args.args:
                    self_name = stmt.args.args[0].arg
                for n in ast.walk(stmt):
                    if not isinstance(n, ast.Assign):
                        continue
                    for t in n.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == self_name):
                            val = n.value
                            if (isinstance(val, ast.Call)
                                    and _terminal(val.func)
                                    in _LOCK_CLASSES) \
                                    or _LOCKISH_RE.search(t.attr):
                                lock_attrs.add(t.attr)
        return lock_attrs, self_name

    def _check_lock_mutation_targets(self, stmt, targets, ctx: _Ctx):
        if (not ctx.lock_attrs or ctx.lock_depth > 0 or ctx.in_init
                or ctx.fn_depth == 0):
            return
        for t in targets:
            attr = self._self_attr_of(t, ctx)
            if attr and attr not in ctx.lock_attrs:
                self._emit("GL301", stmt,
                           f"mutation of `{ctx.self_name}.{attr}` outside "
                           "`with <lock>:` in a lock-owning class — racy "
                           "against locked readers (annotate with "
                           "`# graft: allow(GL301): reason` if the "
                           "caller holds the lock)")

    def _self_attr_of(self, node: ast.AST, ctx: _Ctx) -> Optional[str]:
        """'x' when node is self.x or self.x[...] (mutation targets)."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == ctx.self_name):
            return node.attr
        return None

    # ------------------------------------------------------- expressions
    def _expr(self, node: ast.AST, ctx: _Ctx) -> None:
        if isinstance(node, ast.Call):
            self._call(node, ctx)
            return
        if isinstance(node, ast.Lambda):
            inner = _Ctx(traced=ctx.traced or id(node) in
                         self.traced_lambdas,
                         fn_depth=ctx.fn_depth + 1)
            if inner.traced:
                for a in inner_args(node):
                    inner.tracked.add(a)
            for d in node.args.defaults:
                if _is_mutable_literal(d):
                    self._emit("GL401", d,
                               "mutable default argument in lambda")
            self._expr(node.body, inner)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                if ctx.traced and self._dynamic_iter(gen.iter, ctx):
                    self._emit("GL005", gen.iter,
                               "comprehension over a tracer-derived "
                               "value inside a traced function — use "
                               "lax.scan/vmap")
                self._expr(gen.iter, ctx)
                pred = self._tainted if ctx.traced else self._devicey
                self._update_bindings([gen.target],
                                      pred(gen.iter, ctx), ctx)
                for cond in gen.ifs:
                    self._expr(cond, ctx)
            if isinstance(node, ast.DictComp):
                self._expr(node.key, ctx)
                self._expr(node.value, ctx)
            else:
                self._expr(node.elt, ctx)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, ctx)

    def _call(self, node: ast.Call, ctx: _Ctx) -> None:
        func = node.func
        term = _terminal(func)

        # GL102/GL103 — jit of a fresh function / jit in a loop
        if self.imports.is_jit_family(func):
            if ctx.loop_depth > 0:
                self._emit("GL103", node,
                           f"{term}() called inside a loop — a fresh "
                           "compiled program per iteration")
            # call-site static-arg check against a visible local def
            if node.args and isinstance(node.args[0], ast.Name):
                fn = self.module_defs.get(node.args[0].id)
                if fn is not None:
                    self._check_static_args(node, fn)
        if (isinstance(func, ast.Call)
                and self.imports.is_jit_family(func.func)
                and ctx.loop_depth == 0 and ctx.fn_depth > 0):
            # (in a loop, visiting the inner jit call emits GL103)
            self._emit("GL102", func,
                       "immediately-invoked jit "
                       f"(`{_terminal(func.func)}(f)(...)`) builds a "
                       "fresh traced callable per call — cache the "
                       "jitted function instead")

        # tracer-safety / sync rules
        if isinstance(func, ast.Name) and func.id in ("bool", "int",
                                                      "float") \
                and node.args:
            arg = node.args[0]
            if ctx.traced and self._tainted(arg, ctx):
                self._emit("GL001", node,
                           f"{func.id}() on a tracer-derived value "
                           "inside a traced function")
            elif not ctx.traced and self.hot and self._devicey(arg, ctx):
                self._emit("GL202", node,
                           f"{func.id}() on a device value forces a "
                           "blocking device→host sync")
        elif isinstance(func, ast.Attribute) \
                and func.attr in _MATERIALIZE_METHODS:
            if ctx.traced and self._tainted(func.value, ctx):
                self._emit("GL002", node,
                           f".{func.attr}() on a tracer-derived value "
                           "inside a traced function")
            elif (not ctx.traced and self.hot
                  and self._devicey(func.value, ctx)):
                self._emit("GL201", node,
                           f".{func.attr}() materializes a device value "
                           "on host")
        elif isinstance(func, ast.Attribute) \
                and func.attr == "block_until_ready":
            if ctx.traced and self._tainted(func.value, ctx):
                self._emit("GL002", node,
                           ".block_until_ready() inside a traced "
                           "function")
            elif not ctx.traced and self.hot:
                self._emit("GL203", node,
                           ".block_until_ready() blocks the host on "
                           "device work")
        elif self.imports.is_np_call(func, ("asarray", "array",
                                            "ascontiguousarray")):
            if node.args:
                arg = node.args[0]
                if ctx.traced and self._tainted(arg, ctx):
                    self._emit("GL002", node,
                               f"np.{func.attr}() on a tracer-derived "
                               "value inside a traced function")
                elif (not ctx.traced and self.hot
                      and self._devicey(arg, ctx)):
                    self._emit("GL201", node,
                               f"np.{func.attr}() on a device value "
                               "copies device→host")
        elif term == "device_get":
            args_ = list(node.args) + [k.value for k in node.keywords]
            if ctx.traced and any(self._tainted(a, ctx) for a in args_):
                self._emit("GL002", node,
                           "jax.device_get() inside a traced function")
            elif not ctx.traced and self.hot:
                self._emit("GL201", node,
                           "jax.device_get() copies device→host")

        # GL204 — device arrays into logs / serialization (host, hot)
        if not ctx.traced and self.hot:
            is_log = ((isinstance(func, ast.Name) and func.id == "print")
                      or (isinstance(func, ast.Attribute)
                          and func.attr in _LOG_METHODS
                          and "log" in (_root_name(func) or "").lower())
                      or (isinstance(func, ast.Attribute)
                          and func.attr in ("dumps", "dump")
                          and _root_name(func) == "json"))
            if is_log:
                payload = list(node.args) + [k.value for k in
                                             node.keywords]
                if any(self._devicey(a, ctx) for a in payload):
                    self._emit("GL204", node,
                               "device value passed to logging/"
                               "serialization — forces a sync and can "
                               "pin device buffers; convert via "
                               "float()/np.asarray() under an "
                               "allow-sync, or log host scalars")

        # GL501 — mesh/device-topology construction outside the spine.
        # parallel/mesh.py is the one module allowed to touch these; it
        # is what the rule funnels everyone else toward.
        if not self.path.replace(os.sep, "/").endswith("parallel/mesh.py"):
            if term == "Mesh" and (
                    (isinstance(func, ast.Name)
                     and term in self.imports.from_jax)
                    or (isinstance(func, ast.Attribute)
                        and _root_name(func) in self.imports.jax_roots)):
                self._emit("GL501", node,
                           "jax.sharding.Mesh constructed outside "
                           "parallel/mesh.py — placement decided "
                           "off-spine; use parallel.mesh.make_mesh() or "
                           "MeshContext")
            elif term in ("devices", "local_devices") and (
                    (isinstance(func, ast.Name)
                     and term in self.imports.from_jax)
                    or (isinstance(func, ast.Attribute)
                        and _root_name(func) in self.imports.jax_roots)):
                self._emit("GL501", node,
                           f"jax.{term}() read outside parallel/mesh.py "
                           "— device topology belongs to the spine; use "
                           "parallel.mesh.device_count() or the active "
                           "MeshContext")

        # GL601 — tracer/device values as span or exemplar attributes.
        # The span machinery (observe.trace / observe.reqtrace) promises
        # zero syncs: attrs are host scalars, stringified without
        # touching device buffers. A device value handed to a span
        # emitter (or an exemplar=) defeats that contract at the call
        # site — inside a trace it concretizes the tracer outright.
        if term in _SPAN_EMITTERS or term == "observe":
            attr_vals = [k.value for k in node.keywords
                         if k.arg is not None
                         and (term != "observe" or k.arg == "exemplar")]
            for v in attr_vals:
                if ctx.traced and self._tainted(v, ctx):
                    self._emit("GL601", node,
                               f"tracer-derived value as a {term}() "
                               "attribute inside a traced function — "
                               "span attrs must be host scalars")
                    break
                if not ctx.traced and self.hot \
                        and self._devicey(v, ctx):
                    self._emit("GL601", node,
                               f"device value as a {term}() attribute "
                               "forces a device→host sync on the "
                               "telemetry path — pass a host scalar "
                               "(the sync-free span contract)")
                    break

        # GL602 — full registry/series snapshot on the hot path. The
        # exporters walk EVERY series and sort histogram reservoirs;
        # they are priced for periodic readers (the series sampler, a
        # /metrics scrape), not for a step/request loop — and inside a
        # traced function the read happens at trace time, silently.
        if (term in _SNAPSHOT_READS
                and isinstance(func, ast.Attribute)
                and self._registryish(func.value)
                and (ctx.traced or (self.hot and ctx.loop_depth > 0))):
            where = ("a traced function" if ctx.traced
                     else "a hot-module loop")
            self._emit("GL602", node,
                       f"registry/series {term}() inside {where} — "
                       "O(all metrics) reader work on the hot path; "
                       "hoist the read out (the series sampler thread "
                       "is the periodic reader)")

        # GL301 — mutating method calls on self attrs
        if (isinstance(func, ast.Attribute)
                and func.attr in _MUTATOR_METHODS):
            self._check_lock_mutation_targets(node, [func.value], ctx)

        if isinstance(func, (ast.Call, ast.Lambda)):
            self._expr(func, ctx)
        elif isinstance(func, ast.Attribute):
            self._expr(func.value, ctx)
        for a in node.args:
            self._expr(a, ctx)
        for k in node.keywords:
            self._expr(k.value, ctx)


def inner_args(node: ast.Lambda) -> List[str]:
    args = node.args
    out = [a.arg for a in getattr(args, "posonlyargs", []) + args.args
           + args.kwonlyargs]
    for a in (args.vararg, args.kwarg):
        if a is not None:
            out.append(a.arg)
    return out


# ------------------------------------------------------------- public API

def lint_source(source: str, path: str = "<string>", *,
                hot: Optional[bool] = None,
                hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
                locks: bool = True,
                ) -> List[Finding]:
    """Lint one source string; `hot` overrides path-based hot detection.
    The interprocedural passes (GL7xx lockset + GL8xx shardflow) run
    over the file as a one-module program — built ONCE and shared
    between the two families — unless `locks=False` (lint_paths
    disables them per-file and runs one whole-program pass instead)."""
    if hot is None:
        hot = is_hot(path, hot_prefixes)
    findings = _FileLinter(path, source, hot=hot).run()
    if locks:
        from deeplearning4j_tpu.analysis.callgraph import Program
        from deeplearning4j_tpu.analysis.locks import analyze_lock_program
        from deeplearning4j_tpu.analysis.shardflow import (
            analyze_shardflow_program)
        prog = Program.from_sources([(path, source)])
        findings.extend(analyze_lock_program(
            prog, hot=hot, hot_prefixes=hot_prefixes))
        findings.extend(analyze_shardflow_program(
            prog, hot_prefixes=hot_prefixes))
        findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_file(path: str, *,
              hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
              locks: bool = True,
              ) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        src = f.read()
    rel = os.path.relpath(path).replace(os.sep, "/")
    if rel.startswith(".."):
        rel = path.replace(os.sep, "/")
    return lint_source(src, rel, hot=is_hot(rel, hot_prefixes),
                       hot_prefixes=hot_prefixes, locks=locks)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def lint_files(files: Sequence[str], *,
               hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
               ) -> List[Finding]:
    """Cold lint of an explicit file list: per-file single-module rules,
    then ONE Program build shared by both interprocedural families
    (GL7xx lockset, GL8xx shardflow) — the repo is parsed once, not
    once per family. No select/ignore filtering, no sort; lint_paths
    and the result cache layer on top of this."""
    from deeplearning4j_tpu.analysis.callgraph import Program
    from deeplearning4j_tpu.analysis.locks import analyze_lock_program
    from deeplearning4j_tpu.analysis.shardflow import (
        analyze_shardflow_program)
    findings: List[Finding] = []
    for f in files:
        findings.extend(lint_file(f, hot_prefixes=hot_prefixes,
                                  locks=False))
    prog = Program.from_paths(files)
    findings.extend(analyze_lock_program(prog, hot_prefixes=hot_prefixes))
    findings.extend(analyze_shardflow_program(prog,
                                              hot_prefixes=hot_prefixes))
    return findings


def lint_paths(paths: Sequence[str], *,
               hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
               select: Optional[Sequence[str]] = None,
               ignore: Optional[Sequence[str]] = None,
               cache_path: Optional[str] = None,
               ) -> List[Finding]:
    """Lint files/trees; optional rule-id prefix filters ('GL2' selects
    the whole sync category). The interprocedural GL7xx/GL8xx passes
    run once over ALL the files as one program, so cross-module facts
    (entry-held propagation, donation summaries) see every caller.
    `cache_path` enables the (mtime, sha) result cache — unchanged
    files reuse stored findings and the whole-program pass is skipped
    when no file changed (see analysis/cache.py)."""
    files = iter_python_files(paths)
    if cache_path:
        from deeplearning4j_tpu.analysis.cache import lint_files_cached
        findings = lint_files_cached(files, hot_prefixes=hot_prefixes,
                                     cache_path=cache_path)
    else:
        findings = lint_files(files, hot_prefixes=hot_prefixes)
    if select:
        findings = [f for f in findings
                    if any(f.rule.startswith(s) for s in select)]
    if ignore:
        findings = [f for f in findings
                    if not any(f.rule.startswith(s) for s in ignore)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
