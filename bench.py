"""Benchmark: ResNet-50 training throughput on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference repo publishes no numbers (BASELINE.md); the
north-star target is >=70% of reference A100 images/sec/chip for dl4j-zoo
ResNet-50 data-parallel training. We anchor on a public A100 ResNet-50
training throughput of ~2500 img/s/chip (MLPerf-era mixed precision), so
vs_baseline = value / (0.7 * 2500) — i.e. vs_baseline >= 1.0 meets the
target on a per-chip basis.

Env knobs: BENCH_MODEL=resnet50|lenet, BENCH_BATCH, BENCH_STEPS, BENCH_DTYPE.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np

A100_REF_IMG_S = 2500.0
TARGET_FRACTION = 0.70


def _bench_resnet50(batch: int, steps: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.optim.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50

    model = ResNet50(num_classes=1000, input_shape=(224, 224, 3),
                     updater=Nesterovs(0.1, 0.9))
    conf = dataclasses.replace(model.conf(), dtype=dtype)
    from deeplearning4j_tpu.models import ComputationGraph

    net = ComputationGraph(conf).init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 224, 224, 3)), net.dtype)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])

    step_fn = jax.jit(net.make_step_fn(), donate_argnums=(0, 1, 2))
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                {"input": x}, {"output": y}, None, None, key)
        return loss

    return _timed_ips(run, batch, steps)


def _timed_ips(run, batch: int, steps: int):
    """Two-point timing that is robust to the tunneled TPU runtime, where
    block_until_ready returns early and every host fetch pays seconds of
    relay latency: run N1 and N2 chained steps, force completion by fetching
    only the SCALAR loss each time, and difference out the constant
    latency: per_step = (t2 - t1) / (N2 - N1)."""
    import time

    loss = run(3)           # compile + warmup
    _ = float(loss)
    n1, n2 = max(2, steps // 4), steps
    t0 = time.perf_counter()
    l1 = float(run(n1))
    t1 = time.perf_counter()
    l2 = float(run(n2))
    t2 = time.perf_counter()
    per_step = ((t2 - t1) - (t1 - t0)) / (n2 - n1)
    per_step = max(per_step, 1e-9)
    return batch / per_step, l2


def _bench_lenet(batch: int, steps: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import LeNet
    from deeplearning4j_tpu.models import MultiLayerNetwork

    conf = dataclasses.replace(LeNet().conf(), dtype=dtype)
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 784)), net.dtype)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    step_fn = jax.jit(net.make_step_fn(), donate_argnums=(0, 1, 2))
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss, _ = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                x, y, None, None, key, None)
        return loss

    return _timed_ips(run, batch, steps)


def _bench_lstm(batch: int, steps: int, dtype: str):
    """GravesLSTM language-model-style step with the fused Pallas kernel
    (BASELINE config #3's RNN path; reference precedent: LSTMHelpers)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.recurrent import (
        GravesLSTM, RnnOutputLayer,
    )
    from deeplearning4j_tpu.optim.updaters import Adam

    T, F, H, C = 128, 128, 512, 64
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(0).updater(Adam(1e-3)).activation("tanh")
         .list(GravesLSTM(n_out=H), GravesLSTM(n_out=H),
               RnnOutputLayer(n_out=C, activation="softmax"))
         .set_input_type(InputType.recurrent(F))
         .build())).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, T, F)), jnp.float32)
    y = jnp.asarray(np.eye(C, dtype=np.float32)[
        rng.integers(0, C, (batch, T))])
    step_fn = jax.jit(net.make_step_fn(), donate_argnums=(0, 1, 2))
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss, _ = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                x, y, None, None, key, None)
        return loss

    return _timed_ips(run, batch, steps)


def _bench_vgg16(batch: int, steps: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import ComputationGraph
    from deeplearning4j_tpu.optim.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import VGG16

    model = VGG16(num_classes=1000, input_shape=(224, 224, 3),
                  updater=Nesterovs(0.01, 0.9))
    conf = dataclasses.replace(model.conf(), dtype=dtype)
    from deeplearning4j_tpu.models import MultiLayerNetwork

    net = (ComputationGraph(conf).init() if hasattr(conf, "vertices")
           else MultiLayerNetwork(conf).init())
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 224, 224, 3)), net.dtype)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])
    step_fn = jax.jit(net.make_step_fn(), donate_argnums=(0, 1, 2))
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)
    graph = hasattr(conf, "vertices")

    def run(n):
        loss = None
        for i in range(n):
            if graph:
                state[0], state[1], state[2], loss = step_fn(
                    state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                    {"input": x}, {"output": y}, None, None, key)[:4]
            else:
                state[0], state[1], state[2], loss, _ = step_fn(
                    state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                    x, y, None, None, key, None)
        return loss

    return _timed_ips(run, batch, steps)


def main():
    model = os.environ.get("BENCH_MODEL", "resnet50")
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "40"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    if model == "lenet":
        ips, loss = _bench_lenet(batch, steps, dtype)
        metric = "lenet_mnist_train_images_per_sec"
        vs = ips / 10000.0  # no published reference; nominal anchor
    elif model == "lstm":
        ips, loss = _bench_lstm(min(batch, 64), steps, dtype)
        metric = "lstm_train_sequences_per_sec"
        vs = ips / 100.0  # no published reference; nominal anchor
    elif model == "vgg16":
        ips, loss = _bench_vgg16(min(batch, 128), steps, dtype)
        metric = "vgg16_train_images_per_sec_per_chip"
        vs = ips / (TARGET_FRACTION * 1100.0)  # A100 VGG16 ~1100 img/s
    else:
        ips, loss = _bench_resnet50(batch, steps, dtype)
        metric = "resnet50_train_images_per_sec_per_chip"
        vs = ips / (TARGET_FRACTION * A100_REF_IMG_S)

    unit = "sequences/sec" if model == "lstm" else "images/sec"
    print(json.dumps({
        "metric": metric,
        "value": round(ips, 2),
        "unit": unit,
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
