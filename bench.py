"""Benchmark: ResNet-50 training throughput on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} with
per-step latency and MFU alongside. Never dies silently: the measurement
runs in a CHILD process (a failed TPU backend init is cached for the life
of a jax process, so retry must mean a fresh interpreter); the parent
retries with backoff, degrades through fallback configs (smaller batch ->
LeNet -> CPU), and emits structured JSON with an "error" field even when
every attempt fails.

Baseline: the reference repo publishes no numbers (BASELINE.md); the
north-star target is >=70% of reference A100 images/sec/chip for dl4j-zoo
ResNet-50 data-parallel training. We anchor on a public A100 ResNet-50
training throughput of ~2500 img/s/chip (MLPerf-era mixed precision), so
vs_baseline = value / (0.7 * 2500) — i.e. vs_baseline >= 1.0 meets the
target on a per-chip basis.

Env knobs: BENCH_MODEL=resnet50|vgg16|lstm|sentiment|inception|lenet|transformer
(BENCH_SEQ_LEN sets the transformer rung's sequence length, default 2048),
(comma-separate several to sweep the BASELINE configs, one JSON line
each), BENCH_BATCH, BENCH_STEPS, BENCH_DTYPE, BENCH_ATTEMPT_TIMEOUT (s),
BENCH_NO_FALLBACK=1, BENCH_S2D=1 (space-to-depth ResNet stem, own
metric), BENCH_FUSED=1 (Pallas conv-epilogue fusion, own
metric), BENCH_PROFILE=<dir> (jax.profiler trace of post-warmup steps),
BENCH_STEPS_PER_DISPATCH (recorded in the JSON; sets K for
`--host-overhead`). `python bench.py --host-overhead` (or
BENCH_HOST_OVERHEAD=1) skips the ladder and measures per-step host
overhead of the fit hot path with forced per-step sync vs deferred loss
sync vs K-step fused dispatch (see _host_overhead_main).
`python bench.py --serving` (or BENCH_SERVING=1) drives the REAL
model-serving HTTP server with a closed-loop client pool, comparing the
continuous-batching scheduler against the legacy collect-then-run loop
(throughput + p50/p95/p99 + batch occupancy, reconciled against
/metrics); writes BENCH_serving.json (see _serving_main; knobs:
BENCH_SERVING_CLIENTS/SECS/ROWS/MAX_BATCH/TPU/OUT).
`python bench.py --serving-decode` (or BENCH_SERVING_DECODE=1) runs the
closed-loop prompt→stream decode workload against POST /generate, one
leg per fused-decode K (default K∈{1,4,8}): tokens/sec + round
trips/token + p99 TTFT/ITL reconciled against the /metrics decode
section, zero-recompiles-after-warmup and cross-K greedy parity
asserted; writes BENCH_serving_decode.json (see _serving_decode_main;
knobs: BENCH_DECODE_CLIENTS/ROUNDS/MAX_TOKENS/PROMPT/PREFILL_CHUNK/
KS/OUT).
`python bench.py --serving-fleet` (or BENCH_SERVING_FLEET=1) drives the
FleetRouter over N replica PROCESSES: closed-loop 1→N replica scaling
with router-vs-replica /metrics reconciled exactly, a disaggregated
prefill→handoff→decode greedy-parity probe, and a forced SLO breach →
drain + reroute with zero failed in-flight streams; writes
BENCH_serving_fleet.json (see _serving_fleet_main; knobs:
BENCH_FLEET_REPLICAS/CLIENTS/ROUNDS/MAX_TOKENS/PROMPT/OUT).
`python bench.py --sharding` (or BENCH_SHARDING=1) profiles the GSPMD
sharding spine on a forced-8-device CPU mesh: per-device param +
optimizer-moment bytes replicated vs sharded, syncs/step, post-warmup
recompiles; writes BENCH_sharding.json (see _sharding_main; knobs:
BENCH_SHARDING_OUT/HIDDEN).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

A100_REF_IMG_S = 2500.0
TARGET_FRACTION = 0.70

# process birth, so the adaptive-timing deadline accounts for however
# long compile+warmup already took before timing started
_PROC_T0 = time.monotonic()

# child exit code for "timing differential never dominated latency noise"
# — deterministic for a given noise level, so the ladder must NOT treat
# it like a flaky backend init (no backoff-retry spiral, no batch-halving
# which only shortens steps and makes the condition harder)
_RC_DEGENERATE_TIMING = 17

# Peak dense bf16 matmul throughput per chip, FLOP/s (public spec sheets).
_PEAK_FLOPS = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e device_kind is "TPU v5 lite"
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def _compile(fn, donate, *args):
    """AOT-compile a jitted step once; return (callable, flops_per_step).

    Using the AOT executable for BOTH cost analysis and execution avoids a
    second trace/compile, and cost_analysis gives the exact HLO flop count
    for the MFU figure (PerformanceListener.java:24-60 is the reference's
    measurement seam; MFU is the TPU-native extension of it).
    """
    import jax

    compiled = jax.jit(fn, donate_argnums=donate).lower(*args).compile()
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    return compiled, flops


# Host-sync accounting for the emitted JSON: _timed_ips fetches ONE scalar
# loss per timing leg (that is the sync), so host_sync_per_step = legs/steps
# — the dispatch-depth evidence mirrored by tests/test_perf_guard.py.
_SYNC_STATS = {"syncs": 0, "steps": 0}


def _timed_ips(run, batch: int, steps: int):
    """Two-point timing that is robust to the tunneled TPU runtime, where
    block_until_ready returns early and every host fetch pays seconds of
    relay latency: run N1 and N2 chained steps, force completion by fetching
    only the SCALAR loss each time, and difference out the constant
    latency: per_step = (t2 - t1) / (N2 - N1).

    BENCH_PROFILE=<dir>: capture a jax.profiler trace of a few post-warmup
    steps into <dir> (the utils/profiling.py seam, for MFU analysis)."""
    loss = run(3)           # compile + warmup
    _ = float(loss)
    _SYNC_STATS["syncs"] += 1
    _SYNC_STATS["steps"] += 3
    prof_dir = os.environ.get("BENCH_PROFILE")
    if prof_dir:
        from deeplearning4j_tpu.utils.profiling import trace

        with trace(prof_dir):
            _ = float(run(3))
    n1 = max(2, steps // 4)
    # n2 = 4*n1 keeps the dominance condition below structurally
    # reachable (diff scales with n2-n1 = 3*n1 while the latency
    # constant does not) AND lets each escalation round reuse the
    # previous round's n2 samples as its n1 samples
    n2 = max(steps, 4 * n1)
    last_loss = [0.0]

    def _leg(n):
        t0 = time.perf_counter()
        last_loss[0] = float(run(n))
        _SYNC_STATS["syncs"] += 1
        _SYNC_STATS["steps"] += n
        return time.perf_counter() - t0

    samples = {}

    def _timed(n):
        if n not in samples:
            samples[n] = min(_leg(n), _leg(n))
        return samples[n]

    # Adaptive: with sub-ms steps the differential t(n2)-t(n1) can be
    # smaller than the tunnel's fetch-latency jitter (hundreds of ms),
    # which once produced a nonsense 32e9-seq/s record. Each leg count
    # is timed twice and min-filtered (jitter only ever ADDS time), and
    # the step counts are scaled until the differential dominates the
    # constant latency term. The deadline keeps the escalation's own
    # cost inside the child's attempt timeout, so persistent jitter
    # surfaces as this diagnostic, not as a killed child that the
    # ladder would misread as a tunnel hang. Anchored at PROCESS start
    # (_PROC_T0): compile+warmup already spent part of the attempt
    # budget before timing began.
    deadline = _PROC_T0 + 0.85 * float(
        os.environ.get("BENCH_ATTEMPT_TIMEOUT", "600"))
    for _ in range(6):
        t1 = _timed(n1)
        t2 = _timed(n2)
        diff, denom = t2 - t1, n2 - n1
        # absolute floor AND relative dominance: the tunnel's fetch
        # latency varies by ~0.1-1s between legs even after the
        # min-of-two filter, so a differential under ~2s can still be
        # mostly that variance (observed: a 0.9ms/step acceptance for a
        # true 3.1ms/step model); requiring diff >= 2s bounds the
        # latency-variance error at roughly half, and >= 0.5*t1 keeps
        # the constant term from dominating
        if diff >= 2.0 and diff >= 0.5 * t1:
            break
        # next round costs ~two legs of 4*n2 (n2's samples are reused)
        if time.monotonic() + 8 * t2 > deadline:
            raise RuntimeError(
                f"degenerate timing: diff={diff:.4f}s over {denom} "
                "steps and no time budget left to escalate further "
                "(latency noise exceeded compute signal)")
        n1, n2 = n2, 4 * n2
    else:
        # never reached dominance — a positive diff here is still mostly
        # jitter; refuse to record it as a measurement
        raise RuntimeError(
            f"degenerate timing: diff={diff:.4f}s over {denom} steps "
            "(latency noise exceeded compute signal after 1024x scaling)")
    l2 = last_loss[0]
    per_step = diff / denom
    return batch / per_step, per_step, l2


def _bench_resnet50(batch: int, steps: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.optim.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import ResNet50

    extra = {"stem": "s2d"} if os.environ.get("BENCH_S2D") else {}
    if os.environ.get("BENCH_FUSED"):  # Pallas conv-epilogue fusion
        extra["fused"] = True          # (ops/conv_fused.py)
    model = ResNet50(num_classes=1000, input_shape=(224, 224, 3),
                     updater=Nesterovs(0.1, 0.9), **extra)
    conf = dataclasses.replace(model.conf(), dtype=dtype)
    from deeplearning4j_tpu.models import ComputationGraph

    net = ComputationGraph(conf).init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 224, 224, 3)), net.dtype)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)
    step_fn, flops = _compile(
        net.make_step_fn(), (0, 1, 2),
        state[0], state[1], state[2], jnp.asarray(0, jnp.int32),
        {"input": x}, {"output": y}, None, None, key)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                {"input": x}, {"output": y}, None, None, key)[:4]
        return loss

    return _timed_ips(run, batch, steps) + (flops,)


def _bench_lenet(batch: int, steps: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import LeNet
    from deeplearning4j_tpu.models import MultiLayerNetwork

    conf = dataclasses.replace(LeNet().conf(), dtype=dtype)
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 784)), net.dtype)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)
    step_fn, flops = _compile(
        net.make_step_fn(), (0, 1, 2),
        state[0], state[1], state[2], jnp.asarray(0, jnp.int32),
        x, y, None, None, key, None)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                x, y, None, None, key, None)[:4]
        return loss

    return _timed_ips(run, batch, steps) + (flops,)


def _bench_lstm(batch: int, steps: int, dtype: str):
    """GravesLSTM language-model-style step with the fused Pallas kernel
    (BASELINE config #3's RNN path; reference precedent: LSTMHelpers)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.recurrent import (
        GravesLSTM, RnnOutputLayer,
    )
    from deeplearning4j_tpu.optim.updaters import Adam

    T, F, H, C = 128, 128, 512, 64
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(0).updater(Adam(1e-3)).activation("tanh")
         .list(GravesLSTM(n_out=H), GravesLSTM(n_out=H),
               RnnOutputLayer(n_out=C, activation="softmax"))
         .set_input_type(InputType.recurrent(F))
         .build())).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, T, F)), jnp.float32)
    y = jnp.asarray(np.eye(C, dtype=np.float32)[
        rng.integers(0, C, (batch, T))])
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)
    step_fn, flops = _compile(
        net.make_step_fn(), (0, 1, 2),
        state[0], state[1], state[2], jnp.asarray(0, jnp.int32),
        x, y, None, None, key, None)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                x, y, None, None, key, None)[:4]
        return loss

    return _timed_ips(run, batch, steps) + (flops,)


def _bench_vgg16(batch: int, steps: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import ComputationGraph, MultiLayerNetwork
    from deeplearning4j_tpu.optim.updaters import Nesterovs
    from deeplearning4j_tpu.zoo import VGG16

    model = VGG16(num_classes=1000, input_shape=(224, 224, 3),
                  updater=Nesterovs(0.01, 0.9))
    conf = dataclasses.replace(model.conf(), dtype=dtype)
    net = (ComputationGraph(conf).init() if hasattr(conf, "vertices")
           else MultiLayerNetwork(conf).init())
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 224, 224, 3)), net.dtype)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)
    graph = hasattr(conf, "vertices")
    feats = {"input": x} if graph else x
    labs = {"output": y} if graph else y
    extra = () if graph else (None,)
    step_fn, flops = _compile(
        net.make_step_fn(), (0, 1, 2),
        state[0], state[1], state[2], jnp.asarray(0, jnp.int32),
        feats, labs, None, None, key, *extra)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                feats, labs, None, None, key, *extra)[:4]
        return loss

    return _timed_ips(run, batch, steps) + (flops,)


def _bench_sentiment(batch: int, steps: int, dtype: str):
    """BASELINE config #3: Word2Vec-embedded sequences -> LSTM -> global
    max-pool -> binary sentiment head, with per-timestep feature masks
    (the reference's Word2VecSentimentRNN example shape: 300-d vectors,
    ~256-step reviews)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import (
        GlobalPoolingLayer, OutputLayer,
    )
    from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTM
    from deeplearning4j_tpu.optim.updaters import Adam

    T, F, H, C = 256, 300, 256, 2
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(0).updater(Adam(2e-3)).activation("tanh")
         .list(GravesLSTM(n_out=H),
               GlobalPoolingLayer(pooling="max"),
               OutputLayer(n_out=C, activation="softmax"))
         .set_input_type(InputType.recurrent(F))
         .build())).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, T, F)), jnp.float32)
    lens = rng.integers(T // 4, T, batch)
    fmask = jnp.asarray(
        (np.arange(T)[None, :] < lens[:, None]).astype(np.float32))
    y = jnp.asarray(np.eye(C, dtype=np.float32)[rng.integers(0, C, batch)])
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)
    step_fn, flops = _compile(
        net.make_step_fn(), (0, 1, 2),
        state[0], state[1], state[2], jnp.asarray(0, jnp.int32),
        x, y, fmask, None, key, None)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                x, y, fmask, None, key, None)[:4]
        return loss

    return _timed_ips(run, batch, steps) + (flops,)


def _inception_h5_path() -> str:
    """Generate (once, cached) a full-channel-width InceptionV3 .h5 via
    the genuine-topology builder (tests/keras_fixtures.py — 94 Conv2D +
    94 BN, asymmetric 7x1/1x7 branches, nested concats)."""
    from deeplearning4j_tpu.data.datasets import data_dir

    dest = os.path.join(data_dir(), "bench", "inception_v3_s2.h5")
    if not os.path.exists(dest):
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tests"))
        try:
            from keras_fixtures import make_inception_v3_h5
        finally:
            sys.path.pop(0)
        # scale=2 halves channel widths: full 299x299 topology, ~6M
        # params — keeps one-time h5 generation under a minute.
        # Write-then-rename so a killed generation can't poison the cache.
        tmp = dest + ".tmp"
        make_inception_v3_h5(tmp, scale=2, classes=1000, input_size=299)
        os.replace(tmp, dest)
    return dest


def _bench_inception(batch: int, steps: int, dtype: str):
    """BASELINE config #4: Keras modelimport InceptionV3 .h5 -> graph ->
    inference throughput on TPU (the import-path capability: the
    reference zoo serves imported Keras models for inference)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.keras_import import (
        import_keras_model_and_weights,
    )

    net = import_keras_model_and_weights(_inception_h5_path())
    rng = np.random.default_rng(0)
    # imported weights keep their own dtype (f32 import fidelity)
    x = jnp.asarray(rng.standard_normal((batch, 299, 299, 3)), net.dtype)
    in_name = net.conf.network_inputs[0]

    def fwd(params, states, feats):
        values, _, _ = net._forward(params, states, feats,
                                    train=False, rng=None)
        return values[net.conf.network_outputs[0]]

    fwd_c, flops = _compile(fwd, (), net.params_tree, net.state_tree,
                            {in_name: x})

    def run(n):
        out = None
        for _ in range(n):
            out = fwd_c(net.params_tree, net.state_tree, {in_name: x})
        return jnp.max(out)

    return _timed_ips(run, batch, steps) + (flops,)


def _bench_transformer(batch: int, steps: int, dtype: str):
    """GPT-style causal transformer LM train step at long T — the
    long-context rung (charter extension; no reference counterpart).
    The attention core follows the measured-winner policy
    (`ops/kernel_defaults.attention_policy`): XLA dense or the Pallas
    flash kernel with the blockwise FlashAttention-2 backward, whichever
    the recorded rows say wins at this T (env hatches DL4J_TPU_ATTN* run
    the ablation — each forced configuration gets its own metric name).
    Rate is tokens/sec (= sequences/sec * T). MFU caveat: HLO
    cost_analysis cannot see inside pallas_call, so when flash engages
    the attention share of FLOPs is missing from the mfu field (same
    caveat as the fused-conv rungs, PERF_NOTES)."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.zoo.transformer import TextGenerationTransformer

    T = int(os.environ.get("BENCH_SEQ_LEN", "2048"))
    conf = _dc.replace(
        TextGenerationTransformer(input_shape=(T, 1), d_model=512,
                                  num_heads=8, num_blocks=6).conf(),
        dtype=dtype)
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, (batch, T, 1)), jnp.float32)
    y = jnp.asarray(np.eye(256, dtype=np.float32)[
        rng.integers(0, 256, (batch, T))])
    state = [net.params_tree, net.updater_state, net.state_tree]
    key = jax.random.PRNGKey(0)
    step_fn, flops = _compile(
        net.make_step_fn(), (0, 1, 2),
        state[0], state[1], state[2], jnp.asarray(0, jnp.int32),
        x, y, None, None, key, None)

    def run(n):
        loss = None
        for i in range(n):
            state[0], state[1], state[2], loss = step_fn(
                state[0], state[1], state[2], jnp.asarray(i, jnp.int32),
                x, y, None, None, key, None)[:4]
        return loss

    # tokens/sec: hand _timed_ips the token count per step as the rate unit
    return _timed_ips(run, batch * T, steps) + (flops,)


def _metric_name(model: str) -> str:
    """Metric key for a model, shared by the child AND the ladder's
    degraded/failure paths so every record of one experiment carries one
    name. The s2d stem experiment gets its own metric so it can't mask
    the standard-stem record in bench_last_tpu.json."""
    metric = _BENCHES.get(model, _BENCHES["resnet50"])[1]
    if model == "resnet50":
        tag = ""
        if os.environ.get("BENCH_S2D"):
            tag += "_s2d"
        if os.environ.get("BENCH_FUSED"):
            tag += "_fused"
        if tag:
            return f"resnet50{tag}_train_images_per_sec_per_chip"
    if model == "transformer":
        forced = os.environ.get("DL4J_TPU_ATTN", "").strip().lower()
        if forced in ("flash", "dense"):
            # ablation runs must not overwrite the production-config
            # record in bench_last_tpu.json (keyed by metric)
            return f"transformer_train_tokens_per_sec_attn{forced}"
    return metric


# per-model batch ceilings (memory/compile-time bounds), shared by the
# child and the fallback-ladder planner so degrade rungs actually degrade
_BATCH_CAPS = {"lstm": 64, "vgg16": 128, "sentiment": 32, "inception": 32,
               "transformer": 8}
_FIXED_DTYPE = {"lstm": "float32", "sentiment": "float32",
                "inception": "float32"}

_BENCHES = {
    "resnet50": (_bench_resnet50, "resnet50_train_images_per_sec_per_chip",
                 "images/sec", TARGET_FRACTION * A100_REF_IMG_S),
    "vgg16": (_bench_vgg16, "vgg16_train_images_per_sec_per_chip",
              "images/sec", TARGET_FRACTION * 1100.0),  # A100 VGG16 ~1100
    "lstm": (_bench_lstm, "lstm_train_sequences_per_sec",
             "sequences/sec", 100.0),   # no published reference; nominal
    "sentiment": (_bench_sentiment,
                  "w2v_lstm_sentiment_train_sequences_per_sec",
                  "sequences/sec", 100.0),  # nominal (config #3)
    "inception": (_bench_inception,
                  "keras_inception_v3_inference_images_per_sec",
                  "images/sec", 1000.0),    # nominal (config #4)
    "lenet": (_bench_lenet, "lenet_mnist_train_images_per_sec",
              "images/sec", 10000.0),   # no published reference; nominal
    "transformer": (_bench_transformer, "transformer_train_tokens_per_sec",
                    "tokens/sec", 100000.0),  # nominal (charter extension)
}


def _child_main():
    """One measurement in THIS process; prints detailed JSON on success."""
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    model = os.environ.get("BENCH_MODEL", "resnet50")
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "40"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    dev = jax.devices()[0]
    bench_fn, _, unit, anchor = _BENCHES[model]
    metric = _metric_name(model)
    if model in _BATCH_CAPS:
        batch = min(batch, _BATCH_CAPS[model])

    try:
        ips, per_step, loss, flops = bench_fn(batch, steps, dtype)
    except RuntimeError as e:
        if "degenerate timing" in str(e):
            print(str(e), file=sys.stderr)
            sys.exit(_RC_DEGENERATE_TIMING)
        raise
    # models that fix their own precision regardless of BENCH_DTYPE:
    # lstm/sentiment build float32 nets, inception keeps imported weights
    dtype = _FIXED_DTYPE.get(model, dtype)
    peak = _peak_flops(getattr(dev, "device_kind", ""))
    mfu = (flops / per_step / peak) if (flops and peak) else None
    print(json.dumps({
        "metric": metric,
        "value": round(ips, 2),
        "unit": unit,
        "vs_baseline": round(ips / anchor, 4),
        "per_step_ms": round(per_step * 1e3, 3),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_per_step": flops,
        "batch": batch,
        "dtype": dtype,
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        "final_loss": round(loss, 4),
        # async-dispatch evidence: scalar fetches per executed step in the
        # measured loop, and the dispatch fusion factor in effect
        "host_sync_per_step": (
            round(_SYNC_STATS["syncs"] / _SYNC_STATS["steps"], 6)
            if _SYNC_STATS["steps"] else None),
        "steps_per_dispatch": int(
            os.environ.get("BENCH_STEPS_PER_DISPATCH", "1")),
        "registry": _registry_snapshot(),
        # device-truth telemetry: one DeviceMonitor sample (HBM
        # in-use/peak/limit on TPU; live-array counts everywhere) —
        # attribution series ride in under "registry"
        "devices": _devices_summary(),
    }))


def _devices_summary():
    try:
        from deeplearning4j_tpu.observe.devicemon import (
            device_memory_summary,
        )
        return device_memory_summary()
    except Exception:
        return None


def _registry_snapshot():
    """The process-wide MetricsRegistry snapshot embedded in the BENCH
    blob (compile counts, ETL/prefetch series, listener gauges) —
    `python -m deeplearning4j_tpu.observe.dump BENCH_*.json` renders it."""
    try:
        from deeplearning4j_tpu.observe import get_registry
        return get_registry().snapshot()
    except Exception:
        return None


def _attempt_plans():
    """Ordered (env-overrides, label) fallback ladder. A flaky axon backend
    init (BENCH_r01's failure mode) gets fresh-process retries; a persistent
    one degrades to cheaper configs and finally to the CPU backend so the
    driver always records a structured number."""
    model = os.environ.get("BENCH_MODEL", "resnet50")
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    batch = min(batch, _BATCH_CAPS.get(model, batch))  # label = real batch
    plans = [
        ({}, f"{model} b{batch}"),
        ({}, f"{model} b{batch} retry"),
    ]
    half = max(8, batch // 2)
    if half < batch:        # a capped model at its floor has no half rung
        plans.append(({"BENCH_BATCH": str(half)}, f"{model} b{half}"))
    if not os.environ.get("BENCH_NO_FALLBACK"):
        if model != "lenet":
            plans.append(({"BENCH_MODEL": "lenet", "BENCH_BATCH": "1024"},
                          "lenet fallback"))
        plans.append(({"BENCH_MODEL": "lenet", "BENCH_BATCH": "1024",
                       "BENCH_FORCE_CPU": "1"}, "lenet cpu fallback"))
    return plans


_LAST_TPU_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_last_tpu.json")


def _record_last_tpu(result):
    """Persist the last REAL-TPU measurement PER METRIC (tracked in git on
    purpose: a meaningful artifact like BENCH_r*.json, carried across
    checkouts so a tunnel outage is distinguishable from a perf
    regression; keying by metric keeps a lenet-fallback TPU run from
    masquerading as the resnet50 baseline — variants like the s2d stem
    carry their own metric name for the same reason). Atomic replace so
    a crash can't truncate the file."""
    try:
        blob = {k: result[k] for k in
                ("metric", "value", "unit", "vs_baseline",
                 "per_step_ms", "mfu", "batch", "device")
                if k in result}
        blob["recorded_at_unix"] = time.time()
        records = _load_tpu_records()
        prev = records.get(blob["metric"])
        # in-tree perf regression guard (reference precedent:
        # BenchmarkDataSetIterator throughput fixtures): a new TPU
        # measurement >5% below the carried record is flagged loudly on
        # stderr AND in the record itself — the carried value keeps the
        # best measurement so a flaky slow run can't lower the bar
        if prev and "value" in prev and prev["value"] > 0:
            # compare against the best value ever carried, not just the
            # last record — otherwise repeated sub-5% drops could ratchet
            # the bar down without ever flagging
            best = max(prev["value"], prev.get("best_value", 0.0))
            ratio = blob["value"] / best
            if ratio < 0.95:
                blob["regression_vs_best"] = round(ratio, 4)
                print(f"[bench] PERF REGRESSION: {blob['metric']} "
                      f"{blob['value']:.1f} is {100 * (1 - ratio):.1f}% "
                      f"below the carried TPU record {best:.1f}",
                      file=sys.stderr)
                records[blob["metric"] + "__regressed"] = blob
                blob = prev  # keep the best verified record
            else:
                blob["best_value"] = max(blob["value"], best)
                records.pop(blob["metric"] + "__regressed", None)
        records[blob["metric"]] = blob
        tmp = _LAST_TPU_FILE + ".tmp"
        with open(tmp, "w") as f:
            json.dump(records, f)
        os.replace(tmp, _LAST_TPU_FILE)
    except OSError:
        pass


_HISTORY_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_history.jsonl")


def _append_history(mode, summary):
    """One compact timestamped row per bench invocation, appended to
    BENCH_history.jsonl (every mode, every run — unlike the per-mode
    BENCH_*.json blobs, which only keep the latest). tools/dash.py
    --bench renders the trajectory from these rows."""
    row = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "mode": mode}
    for k in ("metric", "value", "unit", "vs_baseline", "mfu", "batch",
              "config", "platform", "device", "devices",
              "opt_state_shard_factor", "throughput_ratio", "fused_k",
              "speedup_vs_stepwise", "greedy_parity"):
        v = summary.get(k)
        if v is not None and not isinstance(v, (dict, list)):
            row[k] = v
    # per-K decode legs trend as a compact nested list (tools/dash.py
    # ignores keys it doesn't render)
    if isinstance(summary.get("legs"), list):
        row["legs"] = [
            {"k": leg.get("fused_k"),
             "tokens_per_s": leg.get("tokens_per_s"),
             "round_trips_per_token": leg.get("round_trips_per_token"),
             "itl_p99_ms": (leg.get("itl_ms") or {}).get("p99")}
            for leg in summary["legs"]]
    # the {spec on/off} x {native, int8 KV} matrix trends per leg too
    if isinstance(summary.get("spec_matrix"), list):
        row["spec_matrix"] = [
            {"spec": leg.get("spec"), "kv": leg.get("kv_dtype"),
             "k": leg.get("spec_k") or leg.get("fused_k"),
             "tokens_per_s": leg.get("tokens_per_s"),
             "acceptance_rate": leg.get("acceptance_rate"),
             "slots_factor": leg.get("slots_per_chip_factor")}
            for leg in summary["spec_matrix"]]
    # serving-fleet rows: replica count, reroutes/handoffs/migrations,
    # fleet p99 + the 1→N scaling ratio (tools/dash.py fleet panel)
    if isinstance(summary.get("fleet"), dict):
        fl = summary["fleet"]
        row["fleet"] = {k: fl.get(k) for k in (
            "replicas", "reroutes", "handoffs", "migrations",
            "slo_drains", "ttft_p99_ms", "scaling", "reconciled",
            "scrape_age_s", "stale_replicas", "slo_burn")}
    if isinstance(summary.get("scale_legs"), list):
        row["scale_legs"] = [
            {"replicas": leg.get("replicas"),
             "tokens_per_s": leg.get("tokens_per_s"),
             "ttft_p99_ms": (leg.get("ttft_ms") or {}).get("p99"),
             "reconciled": leg.get("metrics_reconciled")}
            for leg in summary["scale_legs"]]
    if isinstance(summary.get("spec"), dict):
        for key in ("tokens_per_s", "acceptance_rate",
                    "speedup_vs_stepwise"):
            v = summary["spec"].get(key)
            if v is not None:
                row["spec_" + key] = v
    # the comm ledger trends as flat comm_* scalars (the dash comm
    # panel reads comm_step_all_reduce_bytes / comm_reconciled)
    if isinstance(summary.get("comm_ledger"), dict):
        cl = summary["comm_ledger"]
        for key, hk in (("measured_step_all_reduce_bytes",
                         "comm_step_all_reduce_bytes"),
                        ("reconciliation_error", "comm_rec_error"),
                        ("reconciled", "comm_reconciled")):
            v = cl.get(key)
            if v is not None:
                row[hk] = v
    # the shared-prefix cache trends as flat prefix_* scalars (the
    # dash sparkline reads prefix_hit_rate / prefix_ttft_speedup)
    if isinstance(summary.get("prefix"), dict):
        for key in ("ttft_speedup", "hit_rate", "cow_forks",
                    "evicted_pages", "no_overlap_ttft_ratio"):
            v = summary["prefix"].get(key)
            if v is not None:
                row["prefix_" + key] = v
    for k, sub in (("ttft_p99_ms", ("ttft_ms", "p99")),
                   ("itl_p99_ms", ("itl_ms", "p99")),
                   ("continuous_p99_ms", ("modes", "continuous",
                                          "p99_ms")),
                   ("continuous_rps", ("modes", "continuous",
                                       "throughput_rps"))):
        v = summary
        for part in sub:
            v = v.get(part) if isinstance(v, dict) else None
        if v is not None:
            row[k] = v
    if summary.get("error"):
        row["error"] = True
    try:
        with open(_HISTORY_FILE, "a") as f:
            f.write(json.dumps(row) + "\n")
    # graft: allow(GL403): history is advisory; never fail the bench
    # over an unwritable artifact dir
    except OSError:
        pass


def _load_tpu_records():
    try:
        with open(_LAST_TPU_FILE) as f:
            blob = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    if "metric" in blob:      # legacy single-record layout
        return {blob["metric"]: blob}
    return blob


def _load_last_tpu(metric):
    return _load_tpu_records().get(metric)


def _host_overhead_main():
    """`--host-overhead` mode: per-step wall time of the fit hot path in a
    host-overhead-dominated regime (a tiny MLP, where device compute is
    negligible and dispatch + scalar fetches are the cost). The legs drive
    the network's REAL fit-path step methods on pre-built same-shape
    batches, so ETL/iterator cost — which the prefetch iterators address
    separately and which is identical across modes — stays out of the
    comparison:

      sync      — `float(net._fit_batch(ds))` every step: the pre-async
                  behaviour, one forced host round-trip per step
      deferred  — `net._fit_batch(ds)` only (loss stays on device), one
                  block at the end: the default executor path
      fused     — `net._fused_dispatch(...)` in K-step lax.scan chunks:
                  the opt-in `steps_per_dispatch=K` path
      floor     — ONE scan over all steps: a single host dispatch for the
                  whole run, i.e. (approximately) pure device compute

    Host overhead per step is (wall − floor); `host_overhead_reduction`
    = (sync − floor) / (fused − floor) — how much of the per-step host
    cost the pipelined path removes. Emits one JSON line like the
    throughput modes so the win lands in the bench trajectory."""
    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optim.updaters import Sgd

    batch = int(os.environ.get("BENCH_BATCH", "64"))
    steps = int(os.environ.get("BENCH_STEPS", "256"))
    k = int(os.environ.get("BENCH_STEPS_PER_DISPATCH", "8"))
    steps -= steps % k          # keep every mode at the same step count
    rng = np.random.default_rng(0)
    dss = [DataSet(rng.standard_normal((batch, 16)).astype(np.float32),
                   np.eye(4, dtype=np.float32)[rng.integers(0, 4, batch)])
           for _ in range(steps)]

    def build():
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.01))
                .list()
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(16)).build())
        return MultiLayerNetwork(conf).init()

    def measure(mode, kk=k):
        net = build()
        if mode in ("fused", "floor"):
            net._fused_dispatch(dss[:kk])        # compile the scan
        else:
            net._fit_batch(dss[0])
        jax.block_until_ready(net.params_tree)
        best = float("inf")
        for _ in range(3):                       # jitter only adds time
            t0 = time.perf_counter()
            if mode == "sync":
                for ds in dss:
                    float(net._fit_batch(ds))
            elif mode == "deferred":
                for ds in dss:
                    net._fit_batch(ds)
            else:
                for i in range(0, steps, kk):
                    net._fused_dispatch(dss[i:i + kk])
            jax.block_until_ready(net.params_tree)
            best = min(best, (time.perf_counter() - t0) / steps * 1e3)
        return best

    sync_ms = measure("sync")
    deferred_ms = measure("deferred")
    fused_ms = measure("fused")
    floor_ms = measure("floor", steps)

    def overhead(ms):
        return max(ms - floor_ms, 0.0)

    def reduction(ms):
        denom = overhead(ms)
        return round(overhead(sync_ms) / denom, 3) if denom > 0 else None

    # tie the JSON to the real fit() loop: host syncs per step as the
    # LossTracker counts them through a default (deferred) fit
    net = build()
    feats = np.concatenate([d.features for d in dss[:32]])
    labs = np.concatenate([d.labels for d in dss[:32]])
    net.fit(feats, labs, batch_size=batch, epochs=2)
    tracked = net._loss_tracker

    # steady-state cost of the device-truth telemetry itself (step-time
    # attribution in the executor + span→flight ring), measured on the
    # same real fit loop with the env kill-switch toggled — PERF_NOTES
    # holds this to <2%
    def fit_wall(attribution_on):
        prev = os.environ.get("DL4J_TPU_ATTRIBUTION")
        os.environ["DL4J_TPU_ATTRIBUTION"] = "1" if attribution_on else "0"
        try:
            net2 = build()
            net2.fit(feats, labs, batch_size=batch, epochs=1)  # warm
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                net2.fit(feats, labs, batch_size=batch, epochs=4)
                jax.block_until_ready(net2.params_tree)
                best = min(best, time.perf_counter() - t0)
            return best
        finally:
            if prev is None:
                os.environ.pop("DL4J_TPU_ATTRIBUTION", None)
            else:
                os.environ["DL4J_TPU_ATTRIBUTION"] = prev

    wall_on = fit_wall(True)
    wall_off = fit_wall(False)
    attribution_overhead_pct = (wall_on - wall_off) / wall_off * 100.0

    from deeplearning4j_tpu.observe.devicemon import device_memory_summary
    t0 = time.perf_counter()
    devices = device_memory_summary()
    devicemon_sample_ms = (time.perf_counter() - t0) * 1e3

    dev = jax.devices()[0]
    out = {
        "metric": "host_overhead",
        "unit": "ms/step",
        "value": round(overhead(fused_ms), 4),
        "batch": batch,
        "steps": steps,
        "steps_per_dispatch": k,
        "sync_ms_per_step": round(sync_ms, 4),
        "deferred_ms_per_step": round(deferred_ms, 4),
        "fused_ms_per_step": round(fused_ms, 4),
        "compute_floor_ms_per_step": round(floor_ms, 4),
        "host_overhead_ms_per_step": {
            "sync": round(overhead(sync_ms), 4),
            "deferred": round(overhead(deferred_ms), 4),
            "fused": round(overhead(fused_ms), 4),
        },
        "host_overhead_reduction": reduction(fused_ms),
        "host_overhead_reduction_deferred_only": reduction(deferred_ms),
        "host_sync_per_step": {
            "sync": 1.0,
            "deferred_fit": round(
                tracked.host_syncs / max(1, tracked.updates), 6),
        },
        "telemetry": {
            "fit_s_attribution_on": round(wall_on, 4),
            "fit_s_attribution_off": round(wall_off, 4),
            "attribution_overhead_pct": round(attribution_overhead_pct, 3),
            "devicemon_sample_ms": round(devicemon_sample_ms, 3),
        },
        "devices": devices,
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        "registry": _registry_snapshot(),
    }
    _append_history("host-overhead", out)
    print(json.dumps(out))


def _serving_main():
    """`--serving` mode: a closed-loop HTTP client pool against the real
    model-serving server, once per scheduling mode:

      collect    — the legacy fixed collect-then-run loop
                   (ParallelInference BATCHED, max_wait_ms collector)
      continuous — the control plane's continuous-batching scheduler
                   (requests join the next dispatch as soon as the
                   device slot frees; no wait timer)

    Closed loop means every client immediately re-issues after each
    response, so both modes face the same offered load and the p50/95/99
    comparison is at (approximately) equal throughput. Client-side
    request counts are reconciled against the server's /metrics totals
    — the observability acceptance check. Emits one JSON line AND
    writes BENCH_serving.json (BENCH_SERVING_OUT overrides)."""
    import jax

    if not os.environ.get("BENCH_SERVING_TPU"):
        jax.config.update("jax_platforms", "cpu")

    import threading
    import urllib.request

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.serving import InferenceServer

    clients = int(os.environ.get("BENCH_SERVING_CLIENTS", "8"))
    secs = float(os.environ.get("BENCH_SERVING_SECS", "6"))
    rows = int(os.environ.get("BENCH_SERVING_ROWS", "1"))
    max_batch = int(os.environ.get("BENCH_SERVING_MAX_BATCH", "32"))
    buckets = [1, 4, 8, 16, 32]

    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=8, activation="softmax"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    payload = json.dumps({
        "ndarray": rng.standard_normal((rows, 16)).tolist()}).encode()

    def post(port, path="/output", data=payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def pct(sorted_ms, q):
        return round(sorted_ms[min(len(sorted_ms) - 1,
                                   int(q * len(sorted_ms)))], 3)

    modes = {}
    for mode in ("collect", "continuous"):
        srv = InferenceServer(net, port=0, scheduler=mode,
                              max_batch_size=max_batch,
                              batch_buckets=buckets, collect_wait_ms=5.0,
                              queue_capacity=max(64, 8 * clients))
        port = srv.start()
        n_warm = 2 * len(buckets)
        for _ in range(n_warm):            # compile every bucket path
            post(port)
        lat_ms = []
        counts = [0] * clients
        lock = threading.Lock()
        t_end = time.monotonic() + secs

        def client(i):
            mine = []
            while time.monotonic() < t_end:
                t0 = time.perf_counter()
                post(port)
                mine.append((time.perf_counter() - t0) * 1e3)
            with lock:
                lat_ms.extend(mine)
                counts[i] = len(mine)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            metrics = json.loads(r.read())
        srv.stop()
        total = sum(counts)
        lat_ms.sort()
        served = metrics["requests"]["completed"]
        modes[mode] = {
            "requests": total,
            "throughput_rps": round(total / wall, 2),
            "p50_ms": pct(lat_ms, 0.50),
            "p95_ms": pct(lat_ms, 0.95),
            "p99_ms": pct(lat_ms, 0.99),
            "mean_ms": round(sum(lat_ms) / len(lat_ms), 3),
            "mean_batch_occupancy_rows":
                metrics["batch"]["mean_occupancy_rows"],
            "occupancy_histogram":
                metrics["batch"]["occupancy_histogram"],
            "metrics_completed": served,
            "metrics_reconciled": served == total + n_warm,
        }

    import jax as _jax

    dev = _jax.devices()[0]
    p99_ratio = (modes["collect"]["p99_ms"]
                 / modes["continuous"]["p99_ms"])
    out = {
        "metric": "serving_continuous_vs_collect_p99_speedup",
        "value": round(p99_ratio, 3),
        "unit": "x",
        "vs_baseline": round(p99_ratio, 3),   # >1: continuous wins p99
        "clients": clients,
        "rows_per_request": rows,
        "duration_s": secs,
        "max_batch_size": max_batch,
        "throughput_ratio": round(
            modes["continuous"]["throughput_rps"]
            / modes["collect"]["throughput_rps"], 3),
        "modes": modes,
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        "registry": _registry_snapshot(),
    }
    dest = os.environ.get("BENCH_SERVING_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_serving.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=1)
    _append_history("serving", out)
    print(json.dumps(out))


def _serving_decode_main():
    """`--serving-decode` mode: closed-loop prompt→stream workload
    against POST /generate — N concurrent clients, each opening a
    session, reading its SSE token stream to completion, and
    immediately opening the next (closed loop). Runs one LEG per
    fused-decode window size K (BENCH_DECODE_KS, default "1,4,8" —
    K=1 is the stepwise baseline) and reports device-truth decode
    serving numbers per leg:

      tokens/sec          aggregate streamed tokens over wall time
      round_trips/token   host dispatches per streamed token (the
                          quantity fused decode divides by K)
      TTFT p50/p99        request-start → first token (client-side)
      ITL p50/p99         gap between consecutive streamed tokens

    each reconciled against the server's /metrics decode section
    (tokens_streamed, window counters, shared-dispatch counters) plus
    the recompile watchdog: after the manager's warmup, session churn
    must cause ZERO compiles at every K (the fixed-shape decode
    contract). Every leg also streams one fixed-prompt greedy probe;
    `greedy_parity` asserts all legs emitted the bit-exact same
    sequence (the fused-decode parity contract, measured end-to-end).

    The primary (largest-K) leg runs its workload TWICE — once with
    request tracing off (the zero-allocation baseline) and once with
    DL4J_TPU_TRACE_SAMPLE=1 (every request traced) — so the artifact
    carries the measured sampled-on overhead
    (`tracing.trace_overhead_pct`, contract <2%) plus one exemplar
    trace tree (`trace`, renderable with tools/trace_view.py). Emits
    one JSON line AND writes BENCH_serving_decode.json
    (BENCH_DECODE_OUT overrides)."""
    import jax

    if not os.environ.get("BENCH_SERVING_TPU"):
        jax.config.update("jax_platforms", "cpu")

    import threading
    import urllib.request

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.attention import (
        PositionEmbeddingLayer, TransformerEncoderBlock,
    )
    from deeplearning4j_tpu.nn.layers.feedforward import (
        EmbeddingSequenceLayer,
    )
    from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
    from deeplearning4j_tpu.observe.watchdog import get_watchdog
    from deeplearning4j_tpu.optim.updaters import Adam
    from deeplearning4j_tpu.serving import InferenceServer

    clients = int(os.environ.get("BENCH_DECODE_CLIENTS", "4"))
    rounds = int(os.environ.get("BENCH_DECODE_ROUNDS", "3"))
    max_tokens = int(os.environ.get("BENCH_DECODE_MAX_TOKENS", "32"))
    prompt_len = int(os.environ.get("BENCH_DECODE_PROMPT", "12"))
    chunk = int(os.environ.get("BENCH_DECODE_PREFILL_CHUNK", "8"))
    ks = sorted({int(x) for x in os.environ.get(
        "BENCH_DECODE_KS", "1,4,8").split(",") if x.strip()})
    V = 32
    probe_prompt = [(i % (V - 1)) + 1 for i in range(prompt_len)]

    def build_net():
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(1e-3)).activation("identity")
                .list(EmbeddingSequenceLayer(n_in=V, n_out=32),
                      PositionEmbeddingLayer(max_length=256),
                      TransformerEncoderBlock(num_heads=4, causal=True,
                                              window=32,
                                              rolling_cache=True,
                                              max_cache=64),
                      RnnOutputLayer(n_out=V, activation="softmax"))
                .set_input_type(InputType.recurrent(1, chunk)).build())
        return MultiLayerNetwork(conf).init()

    def pct(vals, q):
        vals = sorted(vals)
        return (None if not vals else
                round(vals[min(len(vals) - 1, int(q * len(vals)))], 3))

    def build_spec_pair():
        """Target + draft for the speculative matrix legs: the target
        is the bench transformer with a NON-rolling cache (spec decode
        rewinds positions; rolling rings can't) and its block's residual
        write-backs zeroed; the draft is the attention-free trunk
        (embed + pos + output) sharing the target's weights. Under
        pre-norm the silenced block is exact identity, so draft and
        target logits agree bit-for-bit — a distilled-draft stand-in
        that measures the MECHANISM's ceiling (greedy acceptance = 1.0,
        reported, and floored by the perf gate); real-model speedup
        scales with the measured acceptance rate."""
        import jax.numpy as jnp

        def build(blocks):
            layers = [EmbeddingSequenceLayer(n_in=V, n_out=32),
                      PositionEmbeddingLayer(max_length=256)]
            for _ in range(blocks):
                layers.append(TransformerEncoderBlock(
                    num_heads=4, causal=True, window=32,
                    rolling_cache=False, max_cache=128))
            layers.append(RnnOutputLayer(n_out=V, activation="softmax"))
            conf = (NeuralNetConfiguration.builder().seed(0)
                    .updater(Adam(1e-3)).activation("identity")
                    .list(*layers)
                    .set_input_type(InputType.recurrent(1, chunk))
                    .build())
            return MultiLayerNetwork(conf).init()

        tgt, drf = build(1), build(0)
        blk = tgt.params_tree["layer2_transformerencoderblock"]
        for key in ("attn_Wo", "attn_b", "ffn_w2", "ffn_b2"):
            blk[key] = jnp.zeros_like(blk[key])
        for name in drf.params_tree:
            src = ("layer3_rnnoutputlayer"
                   if name == "layer2_rnnoutputlayer" else name)
            drf.params_tree[name] = tgt.params_tree[src]
        return tgt, drf

    def run_leg(fused_k, *, traced_pass, nets=None, spec_k=None,
                kv_dtype=None):
        net, draft = nets() if nets else (build_net(), None)
        srv = InferenceServer(net, port=0, decode_slots=clients,
                              decode_prefill_chunk=chunk,
                              decode_fused_k=fused_k,
                              decode_draft_net=draft,
                              decode_spec_k=spec_k,
                              decode_kv_dtype=kv_dtype,
                              max_batch_size=max(8, clients),
                              queue_capacity=max(64, 8 * clients))
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        compiles_after_warmup = get_watchdog().compiles()

        rng = np.random.default_rng(0)
        lock = threading.Lock()
        ttfts, itls, tok_total, done_sessions = [], [], [0], [0]
        errors = []
        trace_ids = []

        def stream(body):
            req = urllib.request.Request(
                base + "/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            first, prev, n, toks = None, None, 0, []
            with urllib.request.urlopen(req, timeout=120) as r:
                for line in r:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    ev = json.loads(line[6:])
                    tid = ev.get("trace_id")
                    if tid:
                        with lock:
                            trace_ids.append(tid)
                    if "token" in ev:
                        now = time.perf_counter()
                        if first is None:
                            first = (now - t0) * 1e3
                        else:
                            with lock:
                                itls.append((now - prev) * 1e3)
                        prev = now
                        n += 1
                        toks.append(ev["token"])
                    elif "error" in ev:
                        raise RuntimeError(ev["error"])
            return first, n, toks

        def one_generation(seed):
            first, n, _ = stream({
                "prompt_ids": rng.integers(0, V, prompt_len).tolist(),
                "max_tokens": max_tokens, "seed": int(seed),
                "temperature": 0.9})
            if n != max_tokens or first is None:
                raise RuntimeError(f"short stream: {n}/{max_tokens}")
            with lock:
                ttfts.append(first)
                tok_total[0] += n
                done_sessions[0] += 1

        def client(i):
            try:
                for rd in range(rounds):
                    one_generation(i * 1000 + rd)
            except BaseException as e:  # surfaced in the artifact
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

        def run_pass():
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(clients)]
            t_p = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.monotonic() - t_p

        prev_sample = os.environ.pop("DL4J_TPU_TRACE_SAMPLE", None)
        try:
            # pass 1: sampling off — the zero-allocation fast path
            wall_off = run_pass()
            toks_off = tok_total[0]
            wall_on, toks_on = 0.0, 0
            if traced_pass:
                # pass 2: every request traced — the sampled-on tax
                os.environ["DL4J_TPU_TRACE_SAMPLE"] = "1"
                wall_on = run_pass()
                toks_on = tok_total[0] - toks_off
            # the parity probe: one fixed-prompt greedy stream, same
            # at every K by the fused-decode parity contract
            _, _, probe = stream({"prompt_ids": probe_prompt,
                                  "max_tokens": max_tokens,
                                  "greedy": True})
        finally:
            if prev_sample is None:
                os.environ.pop("DL4J_TPU_TRACE_SAMPLE", None)
            else:
                os.environ["DL4J_TPU_TRACE_SAMPLE"] = prev_sample
        wall = wall_off + wall_on
        compile_delta = get_watchdog().compiles() - compiles_after_warmup

        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            metrics = json.loads(r.read())
        trace_block = None
        if trace_ids:
            with urllib.request.urlopen(
                    base + "/trace/" + trace_ids[-1], timeout=10) as r:
                trace_block = json.loads(r.read())
        srv.stop()
        decode = metrics["decode"]["default"]

        toks = tok_total[0]
        streamed = decode["tokens_streamed"]
        disp = decode["dispatches"]["total"]
        spec_block = decode.get("spec_decode") or {}
        leg = {
            "fused_k": fused_k,
            "spec": bool(spec_block.get("enabled")),
            "spec_k": spec_block.get("k") if spec_block.get("enabled")
            else None,
            "kv_dtype": (decode.get("slots") or {}).get("kv_dtype",
                                                        "native"),
            "acceptance_rate": spec_block.get("acceptance_rate"),
            "slots_per_chip_factor": (decode.get("slots") or {}).get(
                "slots_per_chip_factor"),
            "loop": decode["decode_loop"]["kind"],
            "tokens_per_s": round(toks / wall, 2),
            "duration_s": round(wall, 3),
            "sessions_completed": done_sessions[0],
            "round_trips_per_token": (round(disp / streamed, 4)
                                      if streamed else None),
            "windows": decode["dispatches"]["windows"],
            "window_tokens": decode["dispatches"]["window_tokens"],
            "ttft_ms": {"p50": pct(ttfts, 0.50),
                        "p99": pct(ttfts, 0.99)},
            "itl_ms": {"p50": pct(itls, 0.50), "p99": pct(itls, 0.99)},
            "compile_delta_after_warmup": compile_delta,
            "zero_recompiles": compile_delta == 0,
            "metrics_reconciled": (
                streamed == toks + len(probe)
                and decode["sessions"]["completed"]
                == done_sessions[0] + 1),
            "shared_dispatches": decode["dispatches"]["shared"],
            "interleaved": decode["dispatches"]["shared"] > 0,
            "errors": errors,
        }
        if traced_pass:
            leg["tracing"] = {
                "pass_off": {
                    "tokens": toks_off,
                    "duration_s": round(wall_off, 3),
                    "tokens_per_s": round(toks_off / wall_off, 2)},
                "pass_on": {
                    "tokens": toks_on,
                    "duration_s": round(wall_on, 3),
                    "tokens_per_s": (round(toks_on / wall_on, 2)
                                     if wall_on else None)},
                "trace_overhead_pct": round(
                    (1 - (toks_on / wall_on) / (toks_off / wall_off))
                    * 100, 2) if toks_off and toks_on else None,
                "traces_sampled": len(trace_ids),
            }
        return leg, probe, decode, trace_block

    def run_prefix_leg(label, *, cache_on, overlap):
        """One shared-prefix TTFT leg: a NON-rolling (pageable) net,
        one donor stream priming the radix index, then the closed-loop
        clients replaying prompts that share the donor's head. With
        `overlap` the clients reuse a long common stem (distinct
        tails, so every admission may CoW-fork once); without it every
        prompt is fresh (the zero-regression control). `cache_on`
        toggles DL4J_TPU_PREFIX_CACHE, so warm-vs-cold is the same
        binary, same workload, same shapes — only the radix differs.
        A small prefill chunk (4) keeps TTFT prefill-dominated, which
        is what the cache removes; decode windows are identical."""
        p_len = int(os.environ.get("BENCH_DECODE_PAGE_LEN", "8"))
        p_prompt = int(os.environ.get("BENCH_DECODE_PREFIX_PROMPT",
                                      "240"))
        # page-aligned tail: divergence lands exactly on a page
        # boundary, so the whole shared stem is reusable full pages
        p_tail = p_len if overlap else 0
        p_chunk = 2
        p_tokens = 8
        p_cache = p_prompt + 2 * p_tokens
        base = [(i % (V - 1)) + 1 for i in range(p_prompt)]
        prev = os.environ.pop("DL4J_TPU_PREFIX_CACHE", None)
        os.environ["DL4J_TPU_PREFIX_CACHE"] = ("on" if cache_on
                                               else "off")
        try:
            # a long-prompt variant of the bench net: non-rolling (the
            # pageable shape) with a cache big enough that cold prefill
            # dominates TTFT — the regime the radix index targets
            conf = (NeuralNetConfiguration.builder().seed(0)
                    .updater(Adam(1e-3)).activation("identity")
                    .list(EmbeddingSequenceLayer(n_in=V, n_out=32),
                          PositionEmbeddingLayer(max_length=512),
                          TransformerEncoderBlock(
                              num_heads=4, causal=True, window=32,
                              rolling_cache=False, max_cache=p_cache),
                          RnnOutputLayer(n_out=V,
                                         activation="softmax"))
                    .set_input_type(InputType.recurrent(1, p_chunk))
                    .build())
            net = MultiLayerNetwork(conf).init()
            srv = InferenceServer(net, port=0, decode_slots=clients,
                                  decode_prefill_chunk=p_chunk,
                                  decode_fused_k=primary_k,
                                  decode_page_len=p_len,
                                  max_batch_size=max(8, clients),
                                  queue_capacity=max(64, 8 * clients))
            port = srv.start()
            base_url = f"http://127.0.0.1:{port}"
            rng = np.random.default_rng(7)
            lock = threading.Lock()
            ttfts, toks, errors = [], [0], []

            def stream_one(prompt_ids):
                req = urllib.request.Request(
                    base_url + "/generate",
                    data=json.dumps({"prompt_ids": prompt_ids,
                                     "max_tokens": p_tokens,
                                     "greedy": True}).encode(),
                    headers={"Content-Type": "application/json"})
                t0 = time.perf_counter()
                first, n = None, 0
                with urllib.request.urlopen(req, timeout=120) as r:
                    for line in r:
                        line = line.decode().strip()
                        if not line.startswith("data: "):
                            continue
                        ev = json.loads(line[6:])
                        if "token" in ev:
                            if first is None:
                                first = (time.perf_counter() - t0) * 1e3
                            n += 1
                        elif "error" in ev:
                            raise RuntimeError(ev["error"])
                return first, n

            def follower_prompt(uid):
                if overlap:
                    tail = ((rng.integers(1, V, p_tail) + uid) % (V - 1)
                            + 1)
                    return base[:p_prompt - p_tail] + tail.tolist()
                return ((rng.integers(0, p_prompt, p_prompt) + uid)
                        % (V - 1) + 1).tolist()

            def client(i):
                try:
                    for rd in range(rounds):
                        first, n = stream_one(
                            follower_prompt(i * 1000 + rd))
                        if first is None or n != p_tokens:
                            raise RuntimeError(
                                f"short stream: {n}/{p_tokens}")
                        with lock:
                            ttfts.append(first)
                            toks[0] += n
                except BaseException as e:
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")

            # donor pass primes the radix (and, cache-off, is simply
            # one more cold stream — identical work either way)
            stream_one(base)
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(clients)]
            t_p = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t_p
            with urllib.request.urlopen(base_url + "/metrics",
                                        timeout=10) as r:
                metrics = json.loads(r.read())
            srv.stop()
            pc = metrics["decode"]["default"].get("prefix_cache") or {}
            return {
                "label": label,
                "cache": "on" if cache_on else "off",
                "overlap_frac": (round(1 - p_tail / p_prompt, 3)
                                 if overlap else 0.0),
                "prompt_len": p_prompt,
                "page_len": p_len,
                "prefill_chunk": p_chunk,
                "ttft_ms": {"p50": pct(ttfts, 0.50),
                            "p99": pct(ttfts, 0.99)},
                "tokens_per_s": round(toks[0] / wall, 2) if wall
                else None,
                "prefix_cache": {k: pc.get(k) for k in (
                    "enabled", "hit_rate", "hit_tokens", "cow_forks",
                    "evicted_pages", "cached_pages")},
                "errors": errors,
            }
        finally:
            if prev is None:
                os.environ.pop("DL4J_TPU_PREFIX_CACHE", None)
            else:
                os.environ["DL4J_TPU_PREFIX_CACHE"] = prev

    primary_k = ks[-1]
    legs, probes = [], {}
    decode_primary, trace_block = None, None
    for k in ks:
        leg, probe, decode, tb = run_leg(k, traced_pass=(k == primary_k))
        legs.append(leg)
        probes[k] = probe
        if k == primary_k:
            decode_primary, trace_block = decode, tb

    # --- the {spec on/off} x {native, int8 KV} matrix: four legs over
    # the truncated-draft pair. Greedy parity is asserted WITHIN each
    # KV dtype (spec vs non-spec must be bit-exact; int8 legitimately
    # changes numerics vs native, so cross-dtype streams may differ).
    spec_k = int(os.environ.get("BENCH_DECODE_SPEC_K", str(primary_k)))
    spec_legs, spec_probes = [], {}
    spec_decode_native = None
    if os.environ.get("BENCH_DECODE_SPEC", "1") != "0":
        for use_spec, kv in ((False, "native"), (False, "int8"),
                             (True, "native"), (True, "int8")):
            leg, probe, dec, _ = run_leg(
                primary_k, traced_pass=False,
                nets=(build_spec_pair if use_spec else
                      (lambda: (build_spec_pair()[0], None))),
                spec_k=spec_k if use_spec else None,
                kv_dtype=None if kv == "native" else kv)
            spec_legs.append(leg)
            spec_probes[(use_spec, kv)] = probe
            if use_spec and kv == "native":
                spec_decode_native = dec

    # --- shared-prefix TTFT legs: warm (radix on) vs cold (radix off)
    # over the same ~92%-overlap workload, plus a no-overlap control
    # with the cache ON (the zero-regression contract: an enabled but
    # never-hit cache must not tax admission).
    prefix_legs = None
    if os.environ.get("BENCH_DECODE_PREFIX", "1") != "0":
        prefix_legs = [
            run_prefix_leg("warm-shared", cache_on=True, overlap=True),
            run_prefix_leg("cold-shared", cache_on=False, overlap=True),
            run_prefix_leg("no-overlap", cache_on=True, overlap=False),
        ]

    by_k = {leg["fused_k"]: leg for leg in legs}
    primary = by_k[primary_k]
    stepwise = by_k.get(1)
    out = {
        "metric": "serving_decode_tokens_per_s",
        "value": primary["tokens_per_s"],
        "unit": "tokens/s",
        "fused_k": primary_k,
        "clients": clients,
        "rounds": rounds,
        "prompt_len": prompt_len,
        "max_tokens": max_tokens,
        "prefill_chunk": chunk,
        "legs": legs,
        "speedup_vs_stepwise": (
            round(primary["tokens_per_s"] / stepwise["tokens_per_s"], 2)
            if stepwise and stepwise["tokens_per_s"] else None),
        "greedy_parity": all(probes[k] == probes[ks[0]] for k in ks),
        "zero_recompiles": all(leg["zero_recompiles"] for leg in legs),
        "metrics_reconciled": all(leg["metrics_reconciled"]
                                  for leg in legs),
        "errors": [e for leg in legs for e in leg["errors"]],
        "tracing": primary.get("tracing"),
        "server_decode": decode_primary,
        "trace": trace_block,
        "registry": _registry_snapshot(),
    }
    if spec_legs:
        by_cfg = {(leg["spec"], leg["kv_dtype"]): leg
                  for leg in spec_legs}
        spec_on = by_cfg[(True, "native")]
        spec_int8 = by_cfg[(True, "int8")]
        out["spec_matrix"] = spec_legs
        out["spec"] = {
            "spec_k": spec_k,
            "tokens_per_s": spec_on["tokens_per_s"],
            "tokens_per_s_int8": spec_int8["tokens_per_s"],
            "acceptance_rate": spec_on["acceptance_rate"],
            "speedup_vs_stepwise": (
                round(spec_on["tokens_per_s"]
                      / stepwise["tokens_per_s"], 2)
                if stepwise and stepwise["tokens_per_s"] else None),
            "speedup_vs_fused": (
                round(spec_on["tokens_per_s"]
                      / by_cfg[(False, "native")]["tokens_per_s"], 2)
                if by_cfg[(False, "native")]["tokens_per_s"] else None),
            "greedy_parity": (
                spec_probes[(True, "native")]
                == spec_probes[(False, "native")]
                and spec_probes[(True, "int8")]
                == spec_probes[(False, "int8")]),
            "zero_recompiles": all(leg["zero_recompiles"]
                                   for leg in spec_legs),
            "int8_slots_per_chip_factor":
                spec_int8["slots_per_chip_factor"],
            "server_decode": spec_decode_native,
        }
        out["errors"] += [e for leg in spec_legs for e in leg["errors"]]
    if prefix_legs:
        warm, cold, noov = prefix_legs
        w50 = (warm["ttft_ms"]["p50"] or 0)
        c50 = (cold["ttft_ms"]["p50"] or 0)
        n50 = (noov["ttft_ms"]["p50"] or 0)
        out["prefix"] = {
            "page_len": warm["page_len"],
            "overlap_frac": warm["overlap_frac"],
            "ttft_ms_warm_p50": w50 or None,
            "ttft_ms_cold_p50": c50 or None,
            "ttft_speedup": round(c50 / w50, 2) if w50 else None,
            # the headline contract: >=5x TTFT at >=80% prompt overlap
            "ttft_speedup_target_met": (w50 > 0 and c50 / w50 >= 5.0),
            "hit_rate": warm["prefix_cache"].get("hit_rate"),
            "hit_tokens": warm["prefix_cache"].get("hit_tokens"),
            "cow_forks": warm["prefix_cache"].get("cow_forks"),
            "evicted_pages": noov["prefix_cache"].get("evicted_pages"),
            # no-overlap, cache ON vs cache OFF: ~1.0 means the radix
            # probe costs nothing when it never hits
            "no_overlap_ttft_ratio": (round(n50 / c50, 2)
                                      if c50 else None),
            "legs": prefix_legs,
        }
        out["errors"] += [e for leg in prefix_legs
                          for e in leg["errors"]]
    dev = jax.devices()[0]
    out["device"] = getattr(dev, "device_kind", str(dev))
    out["platform"] = dev.platform
    dest = os.environ.get("BENCH_DECODE_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_serving_decode.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=1)
    _append_history("serving-decode", out)
    print(json.dumps(out))
    print(_decode_doc_line(out), file=sys.stderr)


def _decode_doc_line(out) -> str:
    """The doc-facing decode summary sentence, printed verbatim by
    `--serving-decode` — README/ROADMAP/PERF_NOTES quote THIS line, so
    refreshing the docs is a re-run + paste, never a hand-transcription
    (that's how 3292-vs-3364 drift happened)."""
    line = (f"decode serving: {out['value']} tok/s @ K={out['fused_k']} "
            f"fused ({out['speedup_vs_stepwise']}x vs stepwise)")
    sp = out.get("spec")
    if sp:
        line += (f"; spec D={sp['spec_k']}: {sp['tokens_per_s']} tok/s "
                 f"({sp['speedup_vs_stepwise']}x vs stepwise, "
                 f"acceptance {sp['acceptance_rate']}); int8 KV: "
                 f"{sp['int8_slots_per_chip_factor']}x slots/chip at "
                 f"{sp['tokens_per_s_int8']} tok/s")
    pf = out.get("prefix")
    if pf:
        line += (f"; prefix cache: {pf['ttft_speedup']}x TTFT p50 at "
                 f"{pf['overlap_frac']} overlap (hit rate "
                 f"{pf['hit_rate']}, no-overlap ratio "
                 f"{pf['no_overlap_ttft_ratio']})")
    return line


def _kernels_main():
    """`bench.py --kernels`: banded-attention / decode / fused-update
    microbench → BENCH_kernels.json.

    Per shape bucket it records BOTH wall-clock ms (kernel vs its dense
    XLA contender — meaningful on TPU; on CPU the banded side runs
    interpret-mode and the ms column documents only that it ran) and the
    XLA compile-cost flops/bytes of each side. The compile costs are the
    platform-independent evidence the acceptance contract keys on: the
    dense contender's flops grow ~T² across buckets while the banded
    program's grow ~T·w. Dispatch policies are consulted per bucket so
    the kernel_dispatch_total counters land in the embedded registry
    snapshot. Knobs: BENCH_KERNELS_SHAPES="256x32,512x64",
    BENCH_KERNELS_REPS, BENCH_KERNELS_OUT.
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.banded_attention import (
        banded_attention, banded_decode_attention, banded_reference,
        decode_reference,
    )
    from deeplearning4j_tpu.ops.fused_update import (
        adam_update, nesterov_update,
    )
    from deeplearning4j_tpu.ops.kernel_defaults import (
        banded_policy, decode_attention_policy, fused_update_policy,
    )

    on_tpu = jax.default_backend() == "tpu"
    interp = not on_tpu
    reps = int(os.environ.get("BENCH_KERNELS_REPS", "5"))
    shapes = [tuple(int(v) for v in s.split("x"))
              for s in os.environ.get("BENCH_KERNELS_SHAPES",
                                      "256x32,512x64").split(",")]

    def _cost(fn, *args):
        try:
            c = jax.jit(fn).lower(*args).cost_analysis()
            if isinstance(c, (list, tuple)):
                c = c[0] if c else {}
            c = c or {}
            return {"flops": float(c.get("flops") or 0.0),
                    "bytes_accessed": float(c.get("bytes accessed")
                                            or 0.0)}
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _ms(fn, *args):
        f = jax.jit(fn)
        jax.block_until_ready(f(*args))   # compile + warmup
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f(*args))
            dt = (time.perf_counter() - t0) * 1e3
            best = dt if best is None else min(best, dt)
        return round(best, 3)

    b, h, hkv, dh = 2, 4, 2, 64
    buckets = []
    for t, w in shapes:
        key = jax.random.PRNGKey(t)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, t, h, dh), jnp.float32)
        k = jax.random.normal(kk, (b, t, hkv, dh), jnp.float32)
        v = jax.random.normal(kv, (b, t, hkv, dh), jnp.float32)
        pol = banded_policy(t, h, hkv)          # records dispatch
        dense = lambda q, k, v: banded_reference(q, k, v, w, True,
                                                 dh ** -0.5)
        banded = lambda q, k, v: banded_attention(
            q, k, v, w, True, None, 256, 256, interp)
        buckets.append({
            "kind": "banded_attention", "t": t, "window": w,
            "heads": h, "kv_heads": hkv, "head_dim": dh,
            "policy": pol.kind,
            "dense": {"ms": _ms(dense, q, k, v),
                      **_cost(dense, q, k, v)},
            "banded": {"ms": _ms(banded, q, k, v),
                       **_cost(banded, q, k, v)},
        })

    # single-query decode over the KV-cache layout [B, L, Hkv, Dh]
    for cache_len in (512,):
        key = jax.random.PRNGKey(cache_len)
        kq, kk, kv = jax.random.split(key, 3)
        q1 = jax.random.normal(kq, (b, h, dh), jnp.float32)
        ck = jax.random.normal(kk, (b, cache_len, hkv, dh), jnp.float32)
        cv = jax.random.normal(kv, (b, cache_len, hkv, dh), jnp.float32)
        qpos = jnp.full((b,), cache_len - 1, jnp.int32)
        dpol = decode_attention_policy(cache_len, h, hkv)
        ddense = lambda q1, ck, cv: decode_reference(
            q1, ck, cv, qpos, qpos, None, False, dh ** -0.5)
        dband = lambda q1, ck, cv: banded_decode_attention(
            q1, ck, cv, qpos, qpos, window=None, rolling=False,
            block_l=512, interpret=interp)
        buckets.append({
            "kind": "decode_attention", "cache_len": cache_len,
            "heads": h, "kv_heads": hkv, "head_dim": dh,
            "policy": dpol.kind,
            "dense": {"ms": _ms(ddense, q1, ck, cv),
                      **_cost(ddense, q1, ck, cv)},
            "banded": {"ms": _ms(dband, q1, ck, cv),
                       **_cost(dband, q1, ck, cv)},
        })

    # fused optimizer update, one ~1M-element leaf
    n = 1 << 20
    key = jax.random.PRNGKey(7)
    kp, kg = jax.random.split(key)
    p = jax.random.normal(kp, (n,), jnp.float32)
    g = jax.random.normal(kg, (n,), jnp.float32) * 1e-2
    m = jnp.zeros((n,), jnp.float32)
    vv = jnp.zeros((n,), jnp.float32)
    lrbc = jnp.float32(1e-3)

    def adam_xla(p, g, m, vv):
        m2 = 0.9 * m + 0.1 * g
        v2 = 0.999 * vv + 0.001 * g * g
        return p - lrbc * m2 / (jnp.sqrt(v2) + 1e-8), m2, v2

    adam_fused = lambda p, g, m, vv: adam_update(
        p, g, m, vv, lrbc, interpret=interp)
    upol = fused_update_policy("adam")
    buckets.append({
        "kind": "fused_update", "opt": "adam", "n": n, "policy": upol,
        "xla": {"ms": _ms(adam_xla, p, g, m, vv),
                **_cost(adam_xla, p, g, m, vv)},
        "fused": {"ms": _ms(adam_fused, p, g, m, vv),
                  **_cost(adam_fused, p, g, m, vv)},
    })

    dev = jax.devices()[0]
    out = {
        "metric": "kernel_microbench",
        "buckets": buckets,
        "reps": reps,
        "interpret_mode": interp,
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        "registry": _registry_snapshot(),
    }
    dest = os.environ.get("BENCH_KERNELS_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_kernels.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=1)
    _append_history("kernels", out)
    print(json.dumps(out))


def _sharding_main():
    """`bench.py --sharding`: the GSPMD spine's memory + dispatch profile
    on a forced-8-device CPU mesh → BENCH_sharding.json.

    Two legs of the SAME ParallelWrapper fit, differing only in
    `shard_opt_state` (the spine's escape hatch): the replicated leg
    holds full Adam moments on every device, the sharded leg splits
    them across the replica axis (arXiv:2004.13336). Per-device bytes
    come from addressable-shard metadata via
    observe.devicemon.tree_device_bytes (the CPU runtime reports no
    memory_stats), and the blob embeds the devicemon sample list +
    registry snapshot like every other mode. Also records steady-state
    syncs/step and post-warmup recompiles for the sharded leg — the
    numbers the perf gate budgets. Knobs: BENCH_SHARDING_OUT,
    BENCH_SHARDING_HIDDEN (default 256).
    """
    force = "--xla_force_host_platform_device_count=8"
    if "jax" in sys.modules:
        # too late to fake host devices in this process — re-exec with
        # the flag in place and let the child write the blob
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + force).strip()
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_SHARDING"] = "1"
        sys.exit(subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
        ).returncode)
    if force not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + force).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import (
        DenseLayer, OutputLayer,
    )
    from deeplearning4j_tpu.observe.devicemon import tree_device_bytes
    from deeplearning4j_tpu.observe.syncmon import HostSyncMonitor
    from deeplearning4j_tpu.observe.watchdog import (
        RecompileWatchdog, get_watchdog, set_watchdog,
    )
    from deeplearning4j_tpu.optim.updaters import Adam
    from deeplearning4j_tpu.parallel import ParallelWrapper

    hidden = int(os.environ.get("BENCH_SHARDING_HIDDEN", "256"))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((128, 64)).astype(np.float32)
    y = np.eye(8, dtype=np.float32)[rng.integers(0, 8, 128)]

    def build():
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(1e-3)).activation("relu")
                .list(DenseLayer(n_in=64, n_out=hidden),
                      DenseLayer(n_in=hidden, n_out=hidden),
                      OutputLayer(n_in=hidden, n_out=8,
                                  activation="softmax", loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def leg(shard_opt_state):
        prev = set_watchdog(RecompileWatchdog(threshold=10_000))
        try:
            net = build()
            wrap = ParallelWrapper(net, shard_opt_state=shard_opt_state)
            wrap.fit(x, y, batch_size=32, epochs=1)      # compile epoch
            warm0 = get_watchdog().snapshot()["total_compiles"]
            mon = HostSyncMonitor().install()
            try:
                wrap.fit(x, y, batch_size=32, epochs=2)  # steady state
            finally:
                mon.uninstall()
            warm_recompiles = (get_watchdog().snapshot()["total_compiles"]
                               - warm0)
            # comm ledger while the leg's private watchdog is still
            # installed: per-owner-class collective totals plus the
            # single heaviest all-reduce program — the wrapper's train
            # step, the figure the analytic DP expectation prices
            comm = {}
            for tag, orow in get_watchdog().snapshot()["per_owner"].items():
                cols = orow.get("collectives") or {}
                if not cols:
                    continue
                cls = tag.split("@", 1)[0]
                agg = comm.setdefault(cls, {
                    "programs": 0, "ops": 0, "wire_bytes": 0,
                    "step_all_reduce_bytes": 0})
                for srow in cols.values():
                    agg["programs"] += 1
                    agg["ops"] += srow.get("ops", 0)
                    agg["wire_bytes"] += srow.get("wire_bytes", 0)
                    ar = (srow.get("by_kind") or {}).get("all-reduce", {})
                    agg["step_all_reduce_bytes"] = max(
                        agg["step_all_reduce_bytes"],
                        ar.get("wire_bytes", 0))
        finally:
            set_watchdog(prev)
        steps = 2 * (128 // 32)
        params_dev = tree_device_bytes(net.params_tree)
        opt_dev = tree_device_bytes(net.updater_state)

        def mean(d):
            return int(sum(d.values()) / max(len(d), 1))

        return {
            "shard_opt_state": shard_opt_state,
            "per_device_param_bytes": mean(params_dev),
            "per_device_opt_state_bytes": mean(opt_dev),
            "per_device_opt_state_bytes_by_device": dict(
                sorted(opt_dev.items())),
            "syncs_per_step": round(mon.syncs / steps, 3),
            "warm_recompiles": int(warm_recompiles),
            "final_score": float(net.score_),
            "comm": comm,
        }, wrap

    replicated, _ = leg(False)
    sharded, wrap = leg(True)
    total_opt = sum(int(leaf.nbytes) for leaf in
                    jax.tree_util.tree_leaves(wrap.net.updater_state))
    factor = (replicated["per_device_opt_state_bytes"]
              / max(sharded["per_device_opt_state_bytes"], 1))
    # comm-ledger reconciliation: on the REPLICATED (pure-DP) leg the
    # train step's gradient all-reduce must price at the textbook
    # 4 * param_count * (n-1)/n per-device ring bytes — the ledger's
    # one-pass-ring convention makes the two directly comparable (the
    # scalar loss all-reduce adds ~n/(n-1) bytes of slack, inside tol)
    ndev = jax.device_count()
    param_count = sum(int(leaf.size) for leaf in
                      jax.tree_util.tree_leaves(wrap.net.params_tree))
    expected_ar = 4.0 * param_count * (ndev - 1) / ndev
    measured_ar = (replicated["comm"].get("ParallelWrapper", {})
                   .get("step_all_reduce_bytes", 0))
    rec_err = (abs(measured_ar - expected_ar) / expected_ar
               if expected_ar else 1.0)
    comm_ledger = {
        "convention": "one-pass ring: wire = payload*(g-1)/g per device",
        "param_count": param_count,
        "expected_dp_all_reduce_bytes": int(round(expected_ar)),
        "measured_step_all_reduce_bytes": int(measured_ar),
        "reconciliation_error": round(rec_err, 4),
        "reconciled": bool(rec_err <= 0.1),
        "sharded_step_all_reduce_bytes": int(
            sharded["comm"].get("ParallelWrapper", {})
            .get("step_all_reduce_bytes", 0)),
    }
    out = {
        "metric": "sharding_spine",
        "devices": jax.device_count(),
        "mesh_axes": {str(a): int(wrap.mesh.shape[a])
                      for a in wrap.mesh.axis_names},
        "opt_state_bytes_total": int(total_opt),
        "opt_state_shard_factor": round(factor, 2),
        "losses_match": abs(replicated["final_score"]
                            - sharded["final_score"]) < 1e-4,
        "comm_ledger": comm_ledger,
        "replicated": replicated,
        "sharded": sharded,
        "device_memory": _devices_summary(),
        "observability": _registry_snapshot(),
    }
    dest = os.environ.get("BENCH_SHARDING_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_sharding.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=1)
    _append_history("sharding", out)
    print(json.dumps(out))


def _serving_fleet_main():
    """`--serving-fleet` mode: the FleetRouter tier over N replica
    PROCESSES (each its own interpreter + JAX runtime), three legs:

      scale    — closed-loop client pool through the router at each
                 replica count (BENCH_FLEET_REPLICAS, default "1,4"):
                 aggregate streamed tok/s, client-side TTFT/ITL
                 p50/p99, and an EXACT reconcile of the router's
                 /metrics token+request counters against the sum of
                 every replica's own /metrics
      handoff  — disaggregated prefill→handoff→decode greedy probe,
                 bit-identical to the single-replica stream of the
                 same prompt (quantized pages ship as bytes; the
                 decode admission matches the whole stem)
      slo      — a forced burn-rate breach on one replica drains it
                 mid-flight: every in-flight stream completes (zero
                 failed), traffic reroutes to the healthy replica

    The 1→N scaling contract (>2.5x at N=4) is asserted only where
    the host can physically scale (cpu_count >= N or
    BENCH_FLEET_REQUIRE_SCALING=1); a single-core CI box still
    measures and records the ratio. Writes BENCH_serving_fleet.json
    (BENCH_FLEET_OUT overrides) + one fleet row in
    BENCH_history.jsonl."""
    import jax

    if not os.environ.get("BENCH_SERVING_TPU"):
        jax.config.update("jax_platforms", "cpu")

    import threading

    from deeplearning4j_tpu.serving.fleet import client as fclient
    from deeplearning4j_tpu.serving.fleet.launcher import launch_replica
    from deeplearning4j_tpu.serving.fleet.router import FleetRouter

    counts = sorted({int(x) for x in os.environ.get(
        "BENCH_FLEET_REPLICAS", "1,4").split(",") if x.strip()})
    clients = int(os.environ.get("BENCH_FLEET_CLIENTS", "4"))
    rounds = int(os.environ.get("BENCH_FLEET_ROUNDS", "2"))
    max_tokens = int(os.environ.get("BENCH_FLEET_MAX_TOKENS", "16"))
    prompt_len = int(os.environ.get("BENCH_FLEET_PROMPT", "12"))
    V = 32
    spec = {"kind": "bench_lm", "seed": 0, "vocab": V, "chunk": 8,
            "max_cache": 64, "blocks": 1}
    probe = [(i % (V - 1)) + 1 for i in range(prompt_len)]

    def cfg(name, role="mixed", **kw):
        c = {"name": name, "role": role, "model": dict(spec),
             "decode_slots": max(clients, 4), "prefill_chunk": 8,
             "page_len": 16}
        c.update(kw)
        return c

    def pct(vals, q):
        vals = sorted(vals)
        return (None if not vals else
                round(vals[min(len(vals) - 1, int(q * len(vals)))], 3))

    def counter_sum(snap, name):
        return sum(e.get("value", 0) for e in
                   snap.get("series", {}).get(name, ()))

    def hist_p99(snap, name):
        rows = snap.get("series", {}).get(name, ())
        vals = [e.get("p99") for e in rows if e.get("p99") is not None]
        return round(max(vals), 3) if vals else None

    def stream(url, body):
        """One router stream → (tokens, ttft_ms, itls_ms, error)."""
        t0 = time.monotonic()
        last = t0
        toks, itls, ttft, err = [], [], None, None
        for ev in fclient.sse_events(url, "/generate", dict(body),
                                     timeout=300.0):
            if "token" in ev:
                now = time.monotonic()
                if ttft is None:
                    ttft = (now - t0) * 1000.0
                else:
                    itls.append((now - last) * 1000.0)
                last = now
                toks.append(int(ev["token"]))
            if "error" in ev:
                err = ev["error"]
        return toks, ttft, itls, err

    def start_fleet(cfgs, **router_kw):
        procs = [launch_replica(c) for c in cfgs]
        router_kw.setdefault("poll_interval", None)
        router = FleetRouter([(p.name, p.url, p.role) for p in procs],
                             **router_kw)
        rport = router.start()
        return procs, router, f"http://127.0.0.1:{rport}"

    def stop_fleet(procs, router):
        router.stop()
        for p in procs:
            p.terminate()

    # ---------------------------------------------------- scale legs
    legs = []
    probe_tokens = None
    for n in counts:
        procs, router, url = start_fleet(
            [cfg(f"r{i}") for i in range(n)])
        try:
            # warm every replica's compiled windows (and record the
            # single-replica greedy probe as the parity reference)
            for _ in range(n):
                toks, _, _, err = stream(url, {
                    "prompt_ids": probe, "max_tokens": max_tokens,
                    "greedy": True})
                assert err is None, f"warmup failed: {err}"
            if n == counts[0]:
                probe_tokens = toks
            ttfts, itls, lock = [], [], threading.Lock()
            streamed = [0]
            errors = []

            def worker(ci):
                for r in range(rounds):
                    p = [((7 * ci + 3 * r + i) % (V - 1)) + 1
                         for i in range(prompt_len)]
                    toks, ttft, it, err = stream(url, {
                        "prompt_ids": p, "max_tokens": max_tokens,
                        "greedy": True})
                    with lock:
                        if err is not None:
                            errors.append(err)
                        streamed[0] += len(toks)
                        if ttft is not None:
                            ttfts.append(ttft)
                        itls.extend(it)

            t0 = time.monotonic()
            threads = [threading.Thread(target=worker, args=(ci,))
                       for ci in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t0
            assert not errors, f"fleet leg {n}: {errors[:3]}"

            rsnap = fclient.get_json(url, "/metrics", timeout=10.0)
            router_tokens = counter_sum(rsnap, "fleet_tokens_streamed_total")
            router_reqs = counter_sum(rsnap, "fleet_requests_total")
            rep_tokens = rep_reqs = 0
            rep_p99s = {}
            for p in procs:
                snap = fclient.get_json(p.url, "/metrics", timeout=10.0)
                p99s = []
                for d in (snap.get("decode") or {}).values():
                    rep_tokens += int(d.get("tokens_streamed") or 0)
                    p99 = (d.get("ttft_ms") or {}).get("p99")
                    if p99 is not None:
                        p99s.append(p99)
                    rep_reqs += int((d.get("sessions") or {})
                                    .get("opened", 0))
                rep_p99s[p.name] = (round(max(p99s), 3)
                                    if p99s else None)
            client_tokens = streamed[0] + n * len(probe_tokens or ())
            reconciled = (router_tokens == rep_tokens == client_tokens
                          and router_reqs == rep_reqs)
            if not reconciled:
                print(f"[bench] fleet reconcile MISMATCH n={n}: "
                      f"router={router_tokens} replicas={rep_tokens} "
                      f"clients={client_tokens} "
                      f"reqs {router_reqs}/{rep_reqs}", file=sys.stderr)
            legs.append({
                "replicas": n,
                "tokens_per_s": round(streamed[0] / wall, 2),
                "streamed_tokens": streamed[0],
                "wall_s": round(wall, 3),
                "ttft_ms": {"p50": pct(ttfts, 0.50),
                            "p99": pct(ttfts, 0.99)},
                "itl_ms": {"p50": pct(itls, 0.50),
                           "p99": pct(itls, 0.99)},
                "fleet_ttft_p99_ms": hist_p99(rsnap, "fleet_ttft_ms"),
                "replica_ttft_p99_ms": rep_p99s,
                "router_tokens": router_tokens,
                "replica_tokens": rep_tokens,
                "client_tokens": client_tokens,
                "metrics_reconciled": reconciled,
            })
        finally:
            stop_fleet(procs, router)

    scaling = None
    if len(legs) > 1 and legs[0]["tokens_per_s"]:
        scaling = round(legs[-1]["tokens_per_s"]
                        / legs[0]["tokens_per_s"], 3)
    can_scale = (os.cpu_count() or 1) >= counts[-1]
    require = bool(os.environ.get("BENCH_FLEET_REQUIRE_SCALING")) \
        or (can_scale and counts[-1] >= 4)
    if require and scaling is not None and scaling < 2.5:
        print(f"[bench] FLEET SCALING BELOW CONTRACT: "
              f"{counts[0]}→{counts[-1]} replicas = {scaling}x < 2.5x",
              file=sys.stderr)

    # --------------------------------------------------- handoff leg
    procs, router, url = start_fleet(
        [cfg("pf0", role="prefill"), cfg("dc0", role="decode")])
    try:
        toks, _, _, err = stream(url, {"prompt_ids": probe,
                                       "max_tokens": max_tokens,
                                       "greedy": True})
        assert err is None, f"handoff leg failed: {err}"
        rsnap = fclient.get_json(url, "/metrics", timeout=10.0)
        handoff_leg = {
            "tokens": toks,
            "parity_vs_single_replica": toks == probe_tokens,
            "handoffs": counter_sum(rsnap, "fleet_handoffs_total"),
            "handoff_bytes": counter_sum(rsnap,
                                         "fleet_handoff_bytes_total"),
        }
        assert handoff_leg["parity_vs_single_replica"], (
            f"disaggregated stream diverged: {toks} vs {probe_tokens}")
        assert handoff_leg["handoffs"] >= 1
    finally:
        stop_fleet(procs, router)

    # ------------------------------------------------------- SLO leg
    slo_cfg = {"interval": 0.1, "objectives": [
        {"name": "bench-forced-breach",
         "series": "serving_ttft_ms:p99", "threshold": 0.0,
         "budget": 1.0, "fast_s": 30.0, "slow_s": 60.0,
         "burn_threshold": 0.5}]}
    procs, router, url = start_fleet(
        [cfg("s0", slo=slo_cfg), cfg("s1")], auto_drain_on_slo=True)
    try:
        # land traffic on s0 so its breached series has points
        fclient.post_json(procs[0].url, "/generate",
                          {"prompt_ids": probe, "max_tokens": 2,
                           "greedy": True, "stream": False},
                          timeout=120.0)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            hz = fclient.get_json(procs[0].url, "/healthz", timeout=5.0)
            if any(r.startswith("slo firing")
                   for r in hz.get("reasons", ())):
                break
            time.sleep(0.1)
        inflight_err, inflight_ok, lock = [], [0], threading.Lock()

        def inflight(ci):
            toks, _, _, err = stream(url, {
                "prompt_ids": [((ci + i) % (V - 1)) + 1
                               for i in range(prompt_len)],
                "max_tokens": max_tokens, "greedy": True})
            with lock:
                if err is None and toks:
                    inflight_ok[0] += 1
                else:
                    inflight_err.append(err or "empty stream")

        threads = [threading.Thread(target=inflight, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        router.poll_once()          # the breach verdict → drain s0
        for t in threads:
            t.join()
        rsnap = fclient.get_json(url, "/metrics", timeout=10.0)
        post_toks, _, _, err = stream(url, {"prompt_ids": probe,
                                            "max_tokens": 4,
                                            "greedy": True})
        slo_leg = {
            "slo_drains": counter_sum(rsnap, "fleet_slo_drains_total"),
            "migrations": counter_sum(rsnap, "fleet_migrations_total"),
            "reroutes": counter_sum(rsnap, "fleet_reroutes_total"),
            "inflight_completed": inflight_ok[0],
            "inflight_failed": len(inflight_err),
            "failed_requests": counter_sum(rsnap,
                                           "fleet_failed_requests_total"),
            "rerouted_stream_ok": err is None and bool(post_toks),
        }
        # federation health off the same poll tick: scrape freshness,
        # stale count, and the worst fleet-SLO burn (dash.py row)
        fed_rows = router.obsplane.federation.replicas()
        ages = [r["age_s"] for r in fed_rows.values()
                if r["age_s"] is not None]
        slo_snap = router.obsplane.slo_engine.snapshot()
        slo_leg["scrape_age_s"] = max(ages) if ages else None
        slo_leg["stale_replicas"] = sum(
            1 for r in fed_rows.values() if r["stale"])
        slo_leg["slo_burn"] = max(
            (float(s.get("burn_fast") or 0.0)
             for s in slo_snap.get("slos", ())), default=0.0)
        assert slo_leg["slo_drains"] >= 1, "forced SLO breach never drained"
        assert slo_leg["inflight_failed"] == 0, inflight_err[:3]
        assert slo_leg["failed_requests"] == 0
    finally:
        stop_fleet(procs, router)

    best = legs[-1]
    out = {
        "metric": "serving_fleet_tokens_per_s",
        "value": best["tokens_per_s"],
        "unit": "tokens/s",
        "mode": "serving-fleet",
        "platform": jax.devices()[0].platform,
        "replica_counts": counts,
        "clients": clients,
        "rounds": rounds,
        "max_tokens": max_tokens,
        "scaling_1_to_max": scaling,
        "scaling_contract_25x_enforced": bool(require),
        "scale_legs": legs,
        "handoff": handoff_leg,
        "slo": slo_leg,
        "fleet": {
            "replicas": counts[-1],
            "reroutes": slo_leg["reroutes"],
            "handoffs": handoff_leg["handoffs"],
            "migrations": slo_leg["migrations"],
            "slo_drains": slo_leg["slo_drains"],
            "ttft_p99_ms": best["fleet_ttft_p99_ms"],
            "scaling": scaling,
            "reconciled": all(l["metrics_reconciled"] for l in legs),
            "scrape_age_s": slo_leg.get("scrape_age_s"),
            "stale_replicas": slo_leg.get("stale_replicas"),
            "slo_burn": slo_leg.get("slo_burn"),
        },
    }
    path = os.environ.get("BENCH_FLEET_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_serving_fleet.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _append_history("serving-fleet", out)
    print(json.dumps({k: out[k] for k in
                      ("metric", "value", "unit", "scaling_1_to_max",
                       "fleet")}))


def main():
    if "--sharding" in sys.argv or os.environ.get("BENCH_SHARDING"):
        _sharding_main()
        return
    if "--kernels" in sys.argv or os.environ.get("BENCH_KERNELS"):
        _kernels_main()
        return
    if "--serving-decode" in sys.argv or os.environ.get(
            "BENCH_SERVING_DECODE"):
        _serving_decode_main()
        return
    if "--serving-fleet" in sys.argv or os.environ.get(
            "BENCH_SERVING_FLEET"):
        _serving_fleet_main()
        return
    if "--serving" in sys.argv or os.environ.get("BENCH_SERVING"):
        _serving_main()
        return
    if "--host-overhead" in sys.argv or os.environ.get("BENCH_HOST_OVERHEAD"):
        _host_overhead_main()
        return
    if os.environ.get("BENCH_CHILD"):
        _child_main()
        return

    models = os.environ.get("BENCH_MODEL", "resnet50")
    if "," in models:
        # multi-config sweep (BASELINE configs 1-4 in one invocation):
        # one JSON line per model, each through the same child-process
        # ladder + TPU persistence. The driver's default single-model
        # invocation still prints exactly one line.
        try:
            for m in [m.strip() for m in models.split(",") if m.strip()]:
                os.environ["BENCH_MODEL"] = m
                _run_ladder()
        finally:  # restore the caller's comma list — in-process callers
            os.environ["BENCH_MODEL"] = models  # must not see the last model
        return
    _run_ladder()


def _run_ladder():
    timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "600"))
    backoffs = [15.0, 45.0, 90.0]
    errors = []
    hangs = 0
    degens = 0
    plans = _attempt_plans()
    for i, (overrides, label) in enumerate(plans):
        if (hangs >= 2 or degens >= 2) and \
                not overrides.get("BENCH_FORCE_CPU") and \
                i < len(plans) - 1:
            # two full-timeout hangs mean the tunnel is dead (not
            # flaky), and two degenerate timings mean its latency noise
            # deterministically swamps this model's steps — either way,
            # don't burn the remaining TPU rungs, go straight to CPU
            # (which has no tunnel and so no fetch-latency noise)
            errors.append(f"{label}: skipped "
                          f"({'tunnel hung' if hangs >= 2 else 'timing degenerate'} twice)")
            continue
        env = dict(os.environ, BENCH_CHILD="1", **overrides)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            errors.append(f"{label}: timeout after {timeout}s")
            hangs += 1
            continue
        if proc.returncode == 0:
            for line in reversed(proc.stdout.strip().splitlines()):
                try:
                    result = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            else:
                errors.append(f"{label}: rc=0 but no JSON in output")
                continue
            result["attempt"] = i + 1
            result["config"] = label
            if errors:
                result["prior_errors"] = errors
            if result.get("platform") == "tpu":
                _record_last_tpu(result)
            else:
                # degraded (CPU-fallback) number: attach the last verified
                # TPU measurement so an environmental tunnel outage isn't
                # mistaken for a performance regression
                # attach the PRIMARY model's verified-TPU record (what
                # the degraded run failed to re-measure), not the
                # fallback rung's own metric
                model = os.environ.get("BENCH_MODEL", "resnet50")
                last = _load_last_tpu(_metric_name(model))
                if last:
                    result["last_verified_tpu"] = last
            _append_history("ladder", result)
            print(json.dumps(result))
            return
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        errors.append(f"{label}: rc={proc.returncode}: "
                      + " | ".join(tail[-3:]))
        if proc.returncode == _RC_DEGENERATE_TIMING:
            # measurement noise, not backend flakiness: one immediate
            # retry is worth it (noise varies run to run) but backoffs
            # and batch-halving cannot help — shorter steps only make
            # the dominance condition harder. After two, the skip
            # condition above routes straight to the CPU rung.
            degens += 1
            continue
        if i < len(backoffs):
            time.sleep(backoffs[i])

    # Every attempt failed: still emit the structured line (rc 0) so the
    # driver records WHY instead of a bare rc=1 like round 1.
    model = os.environ.get("BENCH_MODEL", "resnet50")
    _, _, unit, _ = _BENCHES.get(model, _BENCHES["resnet50"])
    metric = _metric_name(model)
    out = {
        "metric": metric,
        "value": 0.0,
        "unit": unit,
        "vs_baseline": 0.0,
        "error": errors,
    }
    last = _load_last_tpu(metric)
    if last:
        out["last_verified_tpu"] = last
    _append_history("ladder", out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
